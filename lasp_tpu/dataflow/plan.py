"""Whole-graph dataflow fusion: the propagate plan compiler.

``Graph.propagate`` historically walked the combinator DAG one jitted
sweep at a time — host-side round control, a host sync per sweep to read
the change flags, and a host-side dirty-set walk between sweeps. A
k-round propagate over e edges therefore cost O(k) dispatches (each a
full eligible-subset retrace key) even though every sweep is the same
pure function of the previous states. DrJAX (PAPERS.md: mapped
MapReduce primitives as traceable JAX ops) is the blueprint this module
follows: express the WHOLE combinator graph as one traced program and
let the fixed-point iteration run on device.

The compiler:

1. **closes over the dirty set** — :func:`closure_edges` computes the
   forward closure of the initially-dirty variables through the edge
   DAG (plus never-ran edges). Edges outside the closure can never
   become eligible during this propagate, so they are excluded from the
   traced program entirely (the megakernel is keyed per *dirty-subset
   signature*, exactly like the per-edge path's eligible-subset cache).
2. **levels the DAG** — :func:`level_groups` assigns each closure edge
   a topological level (longest source-distance of its inputs; cyclic
   graphs clamp deterministically) and, WITHIN each level, groups edges
   by stacking signature (``Edge.signature()`` — edge kind × src/dst
   codec × spec, the ``mesh.plan.signature_of`` granularity, shared via
   ``mesh.plan.hashable_signature``).
3. **stacks each group** — a group's tables and source states stack
   leafwise into ``[G, ...]`` super-tensors (``mesh.plan.stack_group``)
   and ONE vmapped contribution evaluates all members; a group that
   fails to trace stacked is demoted to per-edge evaluation, loudly
   (``dataflow_plan_fallbacks_total{reason="stack"}`` + a
   ``RuntimeWarning``), and its members are poisoned non-stackable.
4. **runs the fixed point on device** — the compiled round function
   drives ``ops.fused.fused_dataflow_rounds``' ``lax.while_loop``:
   rounds repeat until the per-dst change flags are all-false (or the
   round budget is hit, surfaced as the same non-convergence error the
   host loop raises). One dispatch replaces O(k·e) — the whole win is
   dispatch/sync amortization.

**Why bit-identity holds** (the contract that made PR 5 safe to ship):
the round body is the SAME Jacobi sweep the per-edge path executes —
every contribution reads the pre-round states, contributions merge into
each dst in edge-index order through the same inflation gate, and
change flags use the same ``~codec.equal``. Stacking is vmap of a
deterministic computation (the same computation, batched) and the
closure argument is the idempotent-join argument frontier scheduling
already relies on: an excluded edge's contribution is already absorbed
in its dst, so re-evaluating it cannot move anything. Level order does
NOT chain values inside a sweep (no Gauss–Seidel): chaining would
converge deep pipelines in fewer rounds but change the observable
per-round state trajectory (threshold watches fire from ingested
states) and the reported round counts — that is the fusion boundary,
per "Fast and Fusiest" (PAPERS.md): fuse everything that preserves the
schedule, cut where it wouldn't (see docs/PERF.md "Dataflow fusion").

The per-dirty-pattern executables live in ONE keyed, FIFO-bounded
:class:`PropagateCache` shared by the fused megakernels and the
per-edge path's eligible-subset round functions (formerly two caches),
with hit/built counters under ``dataflow_plan_*``.
"""

from __future__ import annotations

import dataclasses
import warnings

import jax
import jax.numpy as jnp

from ..mesh.plan import hashable_signature, stack_group
from ..telemetry import counter, gauge

#: cache sentinel: this key failed to build or dispatch; callers fall
#: back to the per-edge path without retrying the compile every run
POISON = object()


def tree_select(pred, a, b):
    """Per-leaf ``where`` over same-structure pytrees (the inflation
    gate — the ``bind`` rule's accept/ignore select)."""
    return jax.tree_util.tree_map(lambda x, y: jnp.where(pred, x, y), a, b)


def merge_into_dst(codec, spec, cur, contribs):
    """The ONE per-dst merge chain every round builder shares: fold the
    contributions into ``cur`` in the given order — join, then accept
    through the inflation gate (the ``bind`` rule,
    ``src/lasp_core.erl:301-311``). The fused megakernel
    (:func:`make_round_fn`), the per-edge subset round, and the
    whole-graph dense round all call THIS, so the bit-identity contract
    between the three schedulers cannot drift."""
    new = cur
    for c in contribs:
        merged = codec.merge(spec, new, c)
        new = tree_select(codec.is_inflation(spec, new, merged), merged, new)
    return new


class PropagateCache:
    """The ONE keyed propagate-executable cache: per-edge
    eligible-subset round functions (``("subset", idx)``) and fused
    megakernels (``("fused", idx)`` — the round budget is a traced
    operand, never part of the key) share one FIFO-bounded dict
    — a long-lived process alternating write sets must not accumulate
    compiled executables without limit, and splitting the bound across
    two caches (the PR 3 shape) doubled the worst case. Hits and builds
    export under ``dataflow_plan_cache_*`` by kind."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._entries: dict = {}

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key):
        ent = self._entries.get(key)
        if ent is not None and ent is not POISON:
            # a POISON lookup is not a hit: nothing compiled is being
            # reused, and counting it would let a fallback storm look
            # like a healthy hit/built ratio
            counter(
                "dataflow_plan_cache_hits_total",
                help="propagate executable-cache hits (subset round fns "
                     "+ fused megakernels share one FIFO-bounded cache)",
                kind=key[0],
            ).inc()
        return ent

    def put(self, key, value) -> None:
        if len(self._entries) >= self.capacity:
            # FIFO eviction (dicts preserve insertion order); a
            # re-compile after eviction is just a warm retrace
            self._entries.pop(next(iter(self._entries)))
        self._entries[key] = value
        counter(
            "dataflow_plan_cache_built_total",
            help="propagate executables built into the shared cache, "
                 "by kind (subset round fn / fused megakernel)",
            kind=key[0],
        ).inc()

    def poison(self, key) -> None:
        """Mark a fused key permanently failed (until the next graph
        rebuild) so every later propagate goes straight per-edge
        instead of re-raising the same compile error."""
        if len(self._entries) >= self.capacity and key not in self._entries:
            self._entries.pop(next(iter(self._entries)))
        self._entries[key] = POISON


def closure_edges(edges, edge_ran, dirty) -> tuple:
    """Indices of every edge that could become eligible during this
    propagate: the forward closure of the initially-dirty variables
    through the DAG, plus never-ran edges (which owe their initial
    evaluation regardless). Deterministic (index-sorted)."""
    dirty = set(dirty)
    # hoisted: this fixpoint walk runs on EVERY propagate (cache hits
    # included), so per-pass set rebuilds would grow O(depth x edges)
    src_sets = [set(e.srcs) for e in edges]
    sel: set = set()
    moved = True
    while moved:
        moved = False
        for i, e in enumerate(edges):
            if i in sel:
                continue
            if not edge_ran[i] or (dirty & src_sets[i]):
                sel.add(i)
                dirty.add(e.dst)
                moved = True
    return tuple(sorted(sel))


def _stack_sig(edge):
    """The normalized stacking signature of one edge, or None (never
    stack): consults the edge's poison flag, its declared signature,
    and the shared hashability rule; the concrete class is part of the
    key so two edge kinds can never collide into one group."""
    if not edge.stackable:
        return None
    raw = edge.signature()
    if raw is None:
        return None
    return hashable_signature(type(edge), *raw)


def level_groups(edges, idx) -> list:
    """``[[edge_index, ...], ...]`` — the closure's edges organized as
    same-signature groups within topological levels, ordered by
    (level, first edge index). Levels come from longest-path relaxation
    over the dst-dependency DAG restricted to ``idx`` (source variables
    sit at depth 0); a cyclic graph stops relaxing at the iteration
    bound, keeping levels finite and deterministic — correctness never
    depends on the leveling, only grouping locality does."""
    sel = [(i, edges[i]) for i in idx]
    depth: dict = {}
    for _ in range(len(sel) + 1):
        moved = False
        for _i, e in sel:
            d = 1 + max((depth.get(s, 0) for s in e.srcs), default=0)
            if depth.get(e.dst, 0) < d:
                depth[e.dst] = d
                moved = True
        if not moved:
            break
    levels: dict = {}
    for i, e in sel:
        lv = min(max((depth.get(s, 0) for s in e.srcs), default=0), len(sel))
        levels.setdefault(lv, []).append(i)
    groups: list = []
    for lv in sorted(levels):
        by_sig: dict = {}
        order: list = []
        for i in levels[lv]:
            sig = _stack_sig(edges[i])
            key = ("__solo__", i) if sig is None else sig
            if key not in by_sig:
                by_sig[key] = []
                order.append(key)
            by_sig[key].append(i)
        groups.extend(by_sig[k] for k in order)
    return groups


def _stacked_struct(tree, g: int):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct((g,) + tuple(x.shape), x.dtype), tree
    )


def guard_groups(edges, groups, states, tables) -> list:
    """The per-group poison guard: every multi-edge group must trace
    its stacked vmapped contribution (shape-level, via ``eval_shape`` —
    no device work); a group that cannot is demoted to per-edge
    singletons LOUDLY (counter + warning) and its members are poisoned
    non-stackable so later compiles skip the attempt."""
    out: list = []
    for g in groups:
        if len(g) == 1:
            out.append(g)
            continue
        e0 = edges[g[0]]
        tab_struct = _stacked_struct(tables[g[0]], len(g))
        src_structs = [
            _stacked_struct(states[e0.srcs[p]], len(g))
            for p in range(len(e0.srcs))
        ]
        try:
            jax.eval_shape(
                jax.vmap(lambda t, *s: e0.contribution(t, *s)),
                tab_struct, *src_structs,
            )
            out.append(g)
        except Exception as exc:  # noqa: BLE001 — the loud-fallback contract
            for i in g:
                edges[i].stackable = False
            counter(
                "dataflow_plan_fallbacks_total",
                help="fused-propagate fallbacks, by reason: `stack` = a "
                     "same-signature group failed to trace stacked and "
                     "was demoted to per-edge evaluation; `dispatch` = "
                     "a fused megakernel failed to build or run and the "
                     "propagate fell back to the per-edge path",
                reason="stack",
            ).inc()
            warnings.warn(
                f"dataflow fusion: group {tuple(g)} "
                f"({type(e0).__name__}/{e0.kind}) cannot stack — demoted "
                f"to per-edge evaluation: {exc!r}",
                RuntimeWarning,
                stacklevel=2,
            )
            out.extend([i] for i in g)
    return out


def make_round_fn(edges, groups, meta, dst_order):
    """One traced Jacobi sweep over the closure: per group, stacked
    (vmapped over ``[G, ...]`` super-tensors) or per-edge contributions
    — all reading the PRE-round states — then per-dst merges in
    edge-index order through the inflation gate. Returns
    ``round_fn(states, tables) -> (new_states, changed: bool[len(
    dst_order)])``, the exact contract of the per-edge subset round."""

    def round_fn(states, tables):
        contribs: dict = {d: [] for d in dst_order}
        for group in groups:
            if len(group) == 1:
                i = group[0]
                e = edges[i]
                c = e.contribution(tables[i], *[states[s] for s in e.srcs])
                contribs[e.dst].append((i, c))
                continue
            e0 = edges[group[0]]
            tabs = stack_group([tables[i] for i in group])
            srcs = [
                stack_group([states[edges[i].srcs[p]] for i in group])
                for p in range(len(e0.srcs))
            ]
            out = jax.vmap(lambda t, *s: e0.contribution(t, *s))(tabs, *srcs)
            for j, i in enumerate(group):
                contribs[edges[i].dst].append(
                    (i, jax.tree_util.tree_map(lambda x, _j=j: x[_j], out))
                )
        new_states = dict(states)
        changed = []
        for dst in dst_order:
            codec, spec = meta[dst]
            cur = states[dst]
            new = merge_into_dst(
                codec, spec, cur,
                [c for _i, c in sorted(contribs[dst], key=lambda t: t[0])],
            )
            changed.append(~codec.equal(spec, cur, new))
            new_states[dst] = new
        return new_states, jnp.stack(changed)

    return round_fn


@dataclasses.dataclass
class FusedPropagate:
    """One compiled megakernel: the jitted while-loop executable plus
    the host-side metadata its dispatches report against."""

    fn: object  # jit((states, tables) -> (states, counts, sweeps, pending))
    dst_order: tuple
    groups: tuple
    n_stacked: int  # edges served by multi-member stacked groups
    sweep_bytes: int  # analytic traffic of ONE sweep (the ledger feed)


def _tree_bytes(tree) -> int:
    return sum(
        int(leaf.size) * int(leaf.dtype.itemsize)
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def sweep_traffic_bytes(edges, idx, states, tables) -> int:
    """Analytic bytes one Jacobi sweep moves: every closure edge reads
    its source states and tables; every distinct dst is read and
    written once through the merge chain (the ideal-traffic convention
    of the ``dataflow_fused`` roofline family)."""
    total = 0
    dsts: set = set()
    for i in idx:
        e = edges[i]
        total += sum(_tree_bytes(states[s]) for s in e.srcs)
        total += _tree_bytes(tables[i])
        dsts.add(e.dst)
    total += sum(2 * _tree_bytes(states[d]) for d in dsts)
    return total


def compile_fused(graph, idx, states, tables) -> FusedPropagate:
    """Compile the megakernel for one dirty-subset signature: level +
    group the closure, guard the groups, close the round function over
    the graph's edge objects, and wrap it in the on-device fixed-point
    loop (``ops.fused.fused_dataflow_rounds``) under one ``jax.jit``.
    The round budget rides as a TRACED scalar operand, so one compiled
    executable serves every ``max_rounds`` a caller passes (the budget
    is not part of the cache key — varying budgets must not churn the
    shared FIFO bound)."""
    from ..ops.fused import fused_dataflow_rounds

    edges = graph.edges
    groups = guard_groups(
        edges, level_groups(edges, idx), states, tables
    )
    dst_order: list = []
    for i in idx:
        if edges[i].dst not in dst_order:
            dst_order.append(edges[i].dst)
    meta = {d: graph._meta(d) for d in dst_order}
    round_fn = make_round_fn(edges, groups, meta, tuple(dst_order))
    n_dsts = len(dst_order)
    # the stats carry: per-sweep changed flags into a modulo-K flight
    # ring, drained on the sync the propagate already performs
    from ..telemetry.device import flight_rounds

    flight_k = flight_rounds()
    fn = jax.jit(
        lambda s, t, lim: fused_dataflow_rounds(
            round_fn, s, t, n_dsts, lim, flight_rounds=flight_k
        )
    )
    gauge(
        "dataflow_plan_groups",
        help="edge groups in the last compiled fused-propagate "
             "megakernel (same-signature edges stack into one vmapped "
             "contribution per group)",
    ).set(len(groups))
    return FusedPropagate(
        fn=fn,
        dst_order=tuple(dst_order),
        groups=tuple(tuple(g) for g in groups),
        n_stacked=sum(len(g) for g in groups if len(g) > 1),
        sweep_bytes=sweep_traffic_bytes(edges, idx, states, tables),
    )
