"""Dataflow graph engine: combinators compiled into one jitted round sweep.

The reference runs one Erlang process per dataflow edge per replica, each
blocking on a strict-threshold read and re-binding its output through the
full quorum path (``src/lasp_process.erl:61-95``, ``src/lasp_core.erl:
639-667``). Here the whole graph is swept synchronously: one jit-compiled
``round(states, tables) -> (states, residual)`` evaluates every edge's
contribution against the *current* states (Jacobi iteration), merges
contributions into each output through the inflation gate (the ``bind``
rule, ``src/lasp_core.erl:291-312``), and reports the number of outputs
whose state changed. Because joins are associative/commutative/idempotent
this reaches the same fixed point as the reference's asynchronous schedule;
a depth-k pipeline converges in k rounds, detected by residual == 0 —
replacing the reference tests' ``timer:sleep`` waits (SURVEY.md §4 caveat).
"""

from __future__ import annotations

import dataclasses
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..lattice.orset import ORSetSpec
from ..lattice.gset import GSetSpec
from . import plan as dplan
from .edges import BindToEdge, Edge, PairwiseEdge, ProductEdge, ProjectEdge


class PairUniverse:
    """Element universe of a product output: term (x, y) <-> index
    lx * ER + ry over the input interners — no separate allocation."""

    def __init__(self, l_elems, r_elems, er_cap: int):
        self.l_elems = l_elems
        self.r_elems = r_elems
        self.er_cap = er_cap

    def __len__(self) -> int:
        return len(self.l_elems) * len(self.r_elems)

    def __contains__(self, term) -> bool:
        # a non-pair term is simply not a member: edges probe membership
        # with arbitrary terms (e.g. an intersection between a product
        # output and a plain set offers the plain set's elements here —
        # caught by the dataflow statem, which crashed on the unpack)
        if not (isinstance(term, tuple) and len(term) == 2):
            return False
        x, y = term
        return x in self.l_elems and y in self.r_elems

    def index_of(self, term) -> int:
        x, y = term
        return self.l_elems.index_of(x) * self.er_cap + self.r_elems.index_of(y)

    def terms(self) -> list:
        return [(x, y) for x in self.l_elems.terms() for y in self.r_elems.terms()]

    def decode_mask(self, mask) -> frozenset:
        out = []
        nl, nr = len(self.l_elems.terms()), len(self.r_elems.terms())
        for i, hit in enumerate(mask):
            if not hit:
                continue
            lx, ry = divmod(i, self.er_cap)
            if lx < nl and ry < nr:
                out.append((self.l_elems.term_of(lx), self.r_elems.term_of(ry)))
        return frozenset(out)


class Graph:
    """Static combinator graph over a :class:`~lasp_tpu.store.Store`.

    Mirrors the reference verb set ``map/filter/fold/union/intersection/
    product/bind_to`` (``src/lasp.erl:252-337``); ``propagate`` replaces the
    background process soup with explicit rounds-to-fixpoint.
    """

    def __init__(self, store):
        self.store = store
        self.edges: list[Edge] = []
        self._jitted = None
        self._round_fn_pure = None  # un-jitted round, vmapped by the mesh layer
        self._var_ids: tuple = ()
        self._clean_mark: tuple | None = None  # (store.mutations, n_edges)
        #: frontier scheduling over edges: has edge i contributed at
        #: least once since the last _build? (a never-run edge is always
        #: eligible)
        self._edge_ran: list = []
        #: the ONE keyed propagate-executable cache (FIFO-64): per-edge
        #: eligible-subset round fns AND fused megakernels, per
        #: dirty-subset signature (dataflow.plan.PropagateCache)
        self._cache = dplan.PropagateCache()
        #: propagate scheduling: "auto" compiles the dirty closure into
        #: one on-device fixed-point megakernel and falls back loudly to
        #: the per-edge host loop on compile/dispatch failure; "fused"
        #: raises instead of falling back; "per_edge" is the historical
        #: one-dispatch-per-sweep path (the bench A/B arm)
        self.fusion: str = "auto"
        #: store.mutations value whose writes this graph has fully
        #: propagated — feeds Store.dirty_since for the initial frontier
        self._dirty_cursor: int = 0

    # -- derived-output declaration -----------------------------------------
    def _derived_orset_spec(self, n_elems: int, token_space: int) -> ORSetSpec:
        return ORSetSpec(
            n_elems=n_elems, n_actors=1, tokens_per_actor=1, token_space=token_space
        )

    def _ensure_output(self, dst, type_name, spec, elems):
        """Declare (or re-layout) the output variable with the derived spec
        dictated by the edge's input spaces."""
        store = self.store
        if dst is None:
            return store.declare(type=type_name, spec=spec, elems=elems)
        if dst in store.ids():
            var = store.variable(dst)
            if var.spec == spec and var.type_name == type_name:
                # layout already matches (e.g. a checkpoint-restored output
                # being re-wired after load): adopt universes, keep state
                self._adopt_universe(var, elems)
                return dst
            if var.elems is not elems or var.spec != spec:
                # an edge already wired to the old layout would keep stale
                # projection tables / reshape against the old spec
                for e in self.edges:
                    if dst in e.srcs or dst == e.dst:
                        raise RuntimeError(
                            f"cannot re-layout {dst}: already wired into a "
                            f"dataflow edge; declare a fresh output instead"
                        )
                store.redeclare_derived(dst, type_name, spec, elems)
            return dst
        return store.declare(id=dst, type=type_name, spec=spec, elems=elems)

    @staticmethod
    def _adopt_universe(var, elems) -> None:
        """Re-wiring an edge onto an existing same-layout output: decide
        which element universe survives. A fresh empty Interner (map/fold/
        union outputs mint their own) loses to the variable's existing one
        (which may hold checkpoint-restored terms the state indexes). A
        non-empty object (filter/bind_to share their SOURCE's interner;
        product passes a PairUniverse derived from the sources) must be
        adopted — after checking index agreement, because the state's bits
        are meaningful only under aligned indices."""
        from ..utils.interning import Interner

        if elems is None or var.elems is elems:
            return
        if isinstance(elems, Interner) and len(elems) == 0:
            return  # fresh mint: keep the existing (possibly restored) one
        for term in var.elems.terms() if hasattr(var.elems, "terms") else ():
            if term not in elems or elems.index_of(term) != var.elems.index_of(term):
                raise RuntimeError(
                    f"cannot adopt universe for {var.id}: existing term "
                    f"{term!r} is missing or re-indexed in the source universe"
                )
        var.elems = elems

    def _add(self, edge: Edge) -> str:
        self.edges.append(edge)
        self._jitted = None
        return edge.dst

    # -- combinator verbs ---------------------------------------------------
    def map(self, src: str, fn, dst: str | None = None, dst_elems: int | None = None):
        """``lasp:map/3`` (``src/lasp.erl:282-285``)."""
        return self._project("map", src, fn, dst, dst_elems)

    def fold(self, src: str, fn, dst: str | None = None, dst_elems: int | None = None):
        """``lasp:fold/3`` — flat-map (``src/lasp.erl:270-273``)."""
        return self._project("fold", src, fn, dst, dst_elems)

    def filter(self, src: str, fn, dst: str | None = None):
        """``lasp:filter/3`` (``src/lasp.erl:258-261``)."""
        return self._project("filter", src, fn, dst, None)

    def _project(self, kind, src, fn, dst, dst_elems):
        store = self.store
        src_var = store.variable(src)
        spec = src_var.spec
        if isinstance(spec, ORSetSpec):
            if kind == "filter":
                out_spec = dataclasses.replace(spec, token_space=spec.n_tokens)
            else:
                d_elems = dst_elems or spec.n_elems * (4 if kind == "fold" else 1)
                out_spec = self._derived_orset_spec(
                    d_elems, spec.n_elems * spec.n_tokens
                )
        elif isinstance(spec, GSetSpec):
            d_elems = (
                spec.n_elems
                if kind == "filter"
                else dst_elems or spec.n_elems * (4 if kind == "fold" else 1)
            )
            out_spec = GSetSpec(n_elems=d_elems)
        else:
            raise TypeError(f"{kind}: unsupported spec {spec!r}")
        if kind == "filter":
            elems = src_var.elems  # same universe, shared interner
        else:
            from ..utils.interning import Interner

            elems = Interner(out_spec.n_elems, kind="element")
        dst = self._ensure_output(dst, src_var.type_name, out_spec, elems)
        return self._add(ProjectEdge(kind, src, dst, fn, store))

    def union(self, left: str, right: str, dst: str | None = None):
        """``lasp:union/3`` (``src/lasp.erl:306-309``)."""
        return self._pairwise("union", left, right, dst)

    def intersection(self, left: str, right: str, dst: str | None = None):
        """``lasp:intersection/3`` (``src/lasp.erl:294-297``)."""
        return self._pairwise("intersection", left, right, dst)

    def _pairwise(self, kind, left, right, dst):
        store = self.store
        l_var, r_var = store.variable(left), store.variable(right)
        ls, rs = l_var.spec, r_var.spec
        from ..utils.interning import Interner

        if isinstance(ls, ORSetSpec):
            if kind == "union":
                out_spec = self._derived_orset_spec(
                    ls.n_elems + rs.n_elems, ls.n_tokens + rs.n_tokens
                )
            else:
                out_spec = self._derived_orset_spec(
                    ls.n_elems, ls.n_tokens + rs.n_tokens
                )
        else:
            n = ls.n_elems + rs.n_elems if kind == "union" else ls.n_elems
            out_spec = GSetSpec(n_elems=n)
        elems = Interner(out_spec.n_elems, kind="element")
        dst = self._ensure_output(dst, l_var.type_name, out_spec, elems)
        return self._add(PairwiseEdge(kind, left, right, dst, store))

    def product(self, left: str, right: str, dst: str | None = None):
        """``lasp:product/3`` (``src/lasp.erl:318-321``)."""
        store = self.store
        l_var, r_var = store.variable(left), store.variable(right)
        ls, rs = l_var.spec, r_var.spec
        if isinstance(ls, ORSetSpec):
            out_spec = self._derived_orset_spec(
                ls.n_elems * rs.n_elems, ls.n_tokens * rs.n_tokens
            )
        else:
            out_spec = GSetSpec(n_elems=ls.n_elems * rs.n_elems)
        elems = PairUniverse(l_var.elems, r_var.elems, rs.n_elems)
        dst = self._ensure_output(dst, l_var.type_name, out_spec, elems)
        return self._add(ProductEdge(left, right, dst, store))

    def bind_to(self, dst: str, src: str):
        """``lasp:bind_to/2`` — dst follows src (``src/lasp.erl:201-207``).
        Argument order mirrors the reference (target first)."""
        store = self.store
        src_var = store.variable(src)
        dst = self._ensure_output(
            dst, src_var.type_name, src_var.spec, src_var.elems
        )
        dst_var = store.variable(dst)
        if dst_var.ivar_payloads is not None and (
            dst_var.ivar_payloads is not src_var.ivar_payloads
        ):
            # dst must adopt src's payload interner so interned ids agree;
            # only sound while dst is still bottom (a written dst already
            # holds ids minted against its own interner)
            is_bottom = bool(
                dst_var.codec.equal(
                    dst_var.spec, dst_var.state, dst_var.codec.new(dst_var.spec)
                )
            )
            if not is_bottom:
                raise RuntimeError(
                    f"bind_to: {dst} already holds a value minted against its "
                    "own payload universe; bind_to requires a bottom target"
                )
            dst_var.ivar_payloads = src_var.ivar_payloads
        return self._add(BindToEdge(src, dst, store))

    # -- provenance -----------------------------------------------------------
    def lineage(self, var_id: str) -> dict:
        """Transitive upstream provenance of ``var_id`` through the
        combinator edges: ``{derived_var: {"kinds": [...], "srcs":
        [...]}}`` for every edge output on some path into ``var_id``
        (including ``var_id`` itself when it is derived). This is the
        map ``lasp_tpu trace --var`` and
        ``telemetry.events.causal_history`` use to pull SOURCE updates
        into a derived variable's history."""
        by_dst: dict = {}
        for e in self.edges:
            by_dst.setdefault(e.dst, []).append(e)
        out: dict = {}
        frontier, visited = [var_id], set()
        while frontier:
            v = frontier.pop()
            if v in visited:
                continue
            visited.add(v)
            for e in by_dst.get(v, ()):
                d = e.describe()
                ent = out.setdefault(v, {"kinds": [], "srcs": []})
                ent["kinds"].append(d["kind"])
                for s in d["srcs"]:
                    if s not in ent["srcs"]:
                        ent["srcs"].append(s)
                    frontier.append(s)
        return out

    # -- round compilation ---------------------------------------------------
    def refresh(self) -> None:
        """Host pass: fold newly interned terms into edge tables, looping
        until universes stop growing (chained edges feed each other)."""
        for _ in range(len(self.edges) + 2):
            changed = [e.refresh(self.store) for e in self.edges]  # no short-circuit
            if not any(changed):
                return
        raise RuntimeError("edge table refresh did not reach a fixed point")

    def _meta(self, var_id):
        var = self.store.variable(var_id)
        return var.codec, var.spec

    def _build(self):
        edges = tuple(self.edges)
        ids = []
        for e in edges:
            for v in (*e.srcs, e.dst):
                if v not in ids:
                    ids.append(v)
        self._var_ids = tuple(ids)
        meta = {v: self._meta(v) for v in ids}

        def round_fn(states, tables):
            contribs: dict[str, list] = {}
            for e, tab in zip(edges, tables):
                c = e.contribution(tab, *[states[s] for s in e.srcs])
                contribs.setdefault(e.dst, []).append(c)
            new_states = dict(states)
            residual = jnp.zeros((), dtype=jnp.int32)
            for dst, cs in contribs.items():
                codec, spec = meta[dst]
                cur = states[dst]
                # merge chain + inflation gate = bind rule, shared with
                # the subset round and the fused megakernel (plan.py)
                new = dplan.merge_into_dst(codec, spec, cur, cs)
                # ¬equal, not strict-inflation: vclock types can change dots
                # under equal clocks (same blindness as the mesh residual)
                residual += (~codec.equal(spec, cur, new)).astype(jnp.int32)
                new_states[dst] = new
            return new_states, residual

        self._round_fn_pure = round_fn
        self._jitted = jax.jit(round_fn)
        # frontier bookkeeping starts over: every edge owes one run
        # against the rebuilt tables/universes; the executable cache
        # (subset round fns + fused megakernels) keys by edge indices,
        # which a rebuild may have re-meant
        self._edge_ran = [False] * len(edges)
        self._cache = dplan.PropagateCache()

    def _subset_round(self, idx: tuple):
        """Jitted sweep over ONLY the edges named by ``idx`` (indices into
        ``self.edges``) — the frontier-scheduled round: skipped edges'
        contributions are unchanged since their last run and already
        merged into their dst (idempotent join), so re-evaluating them is
        pure waste. Returns ``(fn, dst_order)`` where ``fn(states,
        tables) -> (new_states, changed: bool[len(dst_order)])`` — the
        per-dst change flags seed the next round's dirty set. Lives in
        the shared FIFO-bounded :class:`~.plan.PropagateCache` next to
        the fused megakernels (one bound, one hit/built ledger)."""
        key = ("subset", idx)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        sel = [(i, self.edges[i]) for i in idx]
        dst_order: list = []
        for _i, e in sel:
            if e.dst not in dst_order:
                dst_order.append(e.dst)
        meta = {v: self._meta(v) for v in dst_order}

        def round_fn(states, tables):
            contribs: dict[str, list] = {}
            for i, e in sel:
                c = e.contribution(tables[i], *[states[s] for s in e.srcs])
                contribs.setdefault(e.dst, []).append(c)
            new_states = dict(states)
            changed = []
            for dst in dst_order:
                codec, spec = meta[dst]
                cur = states[dst]
                new = dplan.merge_into_dst(codec, spec, cur, contribs[dst])
                changed.append(~codec.equal(spec, cur, new))
                new_states[dst] = new
            return new_states, jnp.stack(changed)

        out = (jax.jit(round_fn), tuple(dst_order))
        self._cache.put(key, out)
        return out

    def propagate(
        self, max_rounds: int | None = None, mode: str | None = None
    ) -> int:
        """Run rounds to the fixed point; ingest results back into the
        store (waking threshold watches). Returns the number of rounds
        that performed work. Replaces every ``timer:sleep`` in the
        reference test suite with a convergence predicate (SURVEY.md §4).

        Scheduling (``mode``, default ``self.fusion`` = ``"auto"``):

        - ``"auto"`` / ``"fused"`` — the dirty closure (edges reachable
          from the store's dirty set, plus never-ran edges) compiles
          into ONE on-device fixed-point megakernel
          (``dataflow.plan``): a leveled, same-signature-stacked Jacobi
          sweep inside a ``lax.while_loop`` that exits when the per-dst
          change flags are all-false — a k-round, e-edge propagate is
          one dispatch instead of O(k·e). Bit-identical values AND
          round counts vs the per-edge path (the closure argument is
          the same idempotent-join argument as edge skipping; the sweep
          body is the same Jacobi round). ``"auto"`` falls back to the
          per-edge path loudly (``dataflow_plan_fallbacks_total`` +
          ``RuntimeWarning``) when a megakernel fails to build or run;
          ``"fused"`` raises instead.
        - ``"per_edge"`` — the historical frontier-scheduled host loop:
          each sweep dispatches ONLY the edges whose sources moved,
          with host-side round control between sweeps (the bench A/B
          arm, and the fallback target)."""
        if not self.edges:
            return 0
        if self._clean_mark == (self.store.mutations, len(self.edges)):
            return 0  # nothing written since the last fixed point
        mode = self.fusion if mode is None else mode
        if mode not in ("auto", "fused", "per_edge"):
            raise ValueError(
                f"unknown propagate mode {mode!r} "
                "(expected auto/fused/per_edge)"
            )
        from ..telemetry import span
        from ..utils.metrics import Timer

        self.refresh()
        if self._jitted is None:
            self._build()
        tables = tuple(e.device_tables() for e in self.edges)
        states = {v: self.store.state(v) for v in self._var_ids}
        limit = max_rounds if max_rounds is not None else len(self.edges) + 1
        dirty = self.store.dirty_since(self._dirty_cursor) & set(
            self._var_ids
        )
        #: shared run accounting, filled by whichever body executed —
        #: the finally-emission lands for the non-convergence raise too
        #: (a runaway propagate is exactly what an operator scrapes for)
        stats = {
            "rounds": 0, "executed": 0, "runs": [0] * len(self.edges),
            "fused": False, "changed_by_dst": None, "flight": None,
        }
        t = Timer()
        try:
            with t, span("dataflow.propagate", edges=len(self.edges)):
                done = False
                if mode != "per_edge":
                    done = self._propagate_fused(
                        states, tables, dirty, limit, stats,
                        strict=(mode == "fused"),
                    )
                if not done:
                    self._propagate_per_edge(
                        states, tables, dirty, limit, stats
                    )
        finally:
            self._emit_propagate_telemetry(stats, t.elapsed)
        pre_ingest = self.store.mutations
        writes = self.store.ingest(states)
        if self.store.mutations == pre_ingest + writes:
            # ingest's own writes ARE the fixed point — mark clean and
            # advance THIS graph's dirty cursor past them (marks are
            # shared store state; other graphs keep their own cursors)
            self._clean_mark = (self.store.mutations, len(self.edges))
            self._dirty_cursor = self.store.mutations
        else:
            # a watch callback wrote during ingest; stay dirty so the next
            # propagate folds that write in
            self._clean_mark = None
        return stats["rounds"]

    def _propagate_fused(
        self, states, tables, dirty, limit, stats, strict: bool
    ) -> bool:
        """The megakernel body: compile (or fetch) the dirty closure's
        fused executable and run the WHOLE fixed point in one dispatch.
        Mutates ``states``/``stats`` in place; returns True when this
        path handled the propagate, False to fall back to the per-edge
        loop (never after device state was consumed — the fused
        executable is functional, so a failed dispatch leaves ``states``
        untouched)."""
        idx = dplan.closure_edges(self.edges, self._edge_ran, dirty)
        if not idx:
            return True  # empty frontier: no edge can move (0 rounds)
        key = ("fused", idx)
        ent = self._cache.get(key)
        if ent is dplan.POISON:
            if strict:
                raise RuntimeError(
                    "fused propagate for this dirty pattern previously "
                    "failed to build; mode='fused' refuses the fallback"
                )
            return False
        from ..telemetry import counter
        from ..telemetry.roofline import get_ledger

        t0 = time.perf_counter()
        try:
            if ent is None:
                ent = dplan.compile_fused(self, idx, states, tables)
                self._cache.put(key, ent)
            # the round budget is a traced operand: one executable per
            # dirty pattern serves every max_rounds a caller passes
            out = ent.fn(states, tables, jnp.int32(limit))
            jax.block_until_ready(out[1:])
        except Exception as exc:  # noqa: BLE001 — the loud-fallback contract
            self._cache.poison(key)
            counter(
                "dataflow_plan_fallbacks_total",
                help="fused-propagate fallbacks, by reason: `stack` = a "
                     "same-signature group failed to trace stacked and "
                     "was demoted to per-edge evaluation; `dispatch` = "
                     "a fused megakernel failed to build or run and the "
                     "propagate fell back to the per-edge path",
                reason="dispatch",
            ).inc()
            if strict:
                raise
            warnings.warn(
                f"fused propagate fell back to the per-edge path "
                f"(dirty closure {idx}): {exc!r}",
                RuntimeWarning,
                stacklevel=3,
            )
            return False
        secs = time.perf_counter() - t0
        new_states, counts, sweeps, pending = out[:4]
        sweeps = int(sweeps)
        pending = bool(pending)
        counts = np.asarray(counts)
        # flight drain: decode the per-sweep changed-flag ring (already
        # synced above) into the window log + the per-sweep records
        # _emit_propagate_telemetry turns into causal events
        joins = len(idx) * sweeps
        if len(out) > 4:
            from ..telemetry import device as tel_flight
            from ..telemetry import registry as _reg

            if _reg.enabled():
                records, overwritten = tel_flight.decode_ring(
                    out[4], sweeps
                )
                stats["flight"] = {
                    "records": records,
                    "overwritten": overwritten,
                    "dst_order": ent.dst_order,
                }
                if not overwritten:
                    # exact: total (dst, sweep) inflations the window
                    # actually performed, vs the every-edge-every-sweep
                    # upper bound
                    joins = sum(sum(r) for r in records)
                tel_flight.record_window(tel_flight.FlightWindow(
                    family="dataflow_fused",
                    columns=tuple(ent.dst_order),
                    rounds=sweeps,
                    overwritten=overwritten,
                    records=records,
                    seconds=secs,
                    quiescent=not pending,
                ))
        get_ledger().record(
            "dataflow_fused", "Graph",
            n_replicas=1, fanout=len(idx), seconds=secs,
            row_bytes=ent.sweep_bytes, window=sweeps, rounds=sweeps,
            bytes_moved=ent.sweep_bytes * sweeps,
            joins=joins, n_vars=len(idx),
        )
        for i in idx:
            self._edge_ran[i] = True
            stats["runs"][i] = sweeps
        stats["executed"] = sweeps
        stats["fused"] = True
        stats["changed_by_dst"] = {
            d: int(c) for d, c in zip(ent.dst_order, counts.tolist())
        }
        # productive sweeps: the loop exits right after its first
        # unproductive sweep (the convergence check), so rounds =
        # sweeps - 1 — unless the budget ran out mid-flight, where every
        # executed sweep was productive (the host loop counts the same)
        stats["rounds"] = sweeps if pending else max(sweeps - 1, 0)
        states.update(
            {v: new_states[v] for v in ent.dst_order}
        )
        if pending:
            raise RuntimeError(
                f"dataflow did not converge within {limit} "
                "rounds (cyclic graph? raise max_rounds)"
            )
        return True

    def _propagate_per_edge(self, states, tables, dirty, limit, stats):
        """The historical frontier-scheduled host loop: each sweep
        dispatches ONLY the edges whose sources moved — seeded from the
        store's dirty set, then per-round from the dsts the previous
        sweep changed. An edge whose sources are all clean contributes
        exactly what it already merged (idempotent join), so skipping
        it cannot change the fixed point or the round count — same
        states, same rounds, less work. Mutates ``states``/``stats``
        in place."""
        cur = states
        for _ in range(limit):
            eligible = tuple(
                i
                for i, e in enumerate(self.edges)
                if not self._edge_ran[i] or (dirty & set(e.srcs))
            )
            if not eligible:
                break  # empty frontier: no edge can move
            fn, dst_order = self._subset_round(eligible)
            cur, changed_vec = fn(cur, tables)
            stats["executed"] += 1
            for i in eligible:
                self._edge_ran[i] = True
                stats["runs"][i] += 1
            dirty = {
                d
                for d, c in zip(dst_order, np.asarray(changed_vec).tolist())
                if c
            }
            if not dirty:
                break
            stats["rounds"] += 1
        else:
            raise RuntimeError(
                f"dataflow did not converge within {limit} "
                "rounds (cyclic graph? raise max_rounds)"
            )
        states.update(cur)

    def _emit_propagate_telemetry(self, stats, elapsed: float) -> None:
        """The propagate run's whole emission path, factored out so the
        overhead guard (``telemetry.overhead``) can price the fused hot
        path exactly: counters, the per-kind recompute/skip accounting,
        and the coarse causal-log records (including the fused window's
        per-dst changed counts — the summary that keeps ``lasp_tpu
        trace --var`` lineage from silently dropping fused rounds)."""
        from ..telemetry import counter, histogram
        from ..telemetry import events as tel_events

        executed = stats["executed"]
        runs = stats["runs"]
        counter(
            "dataflow_rounds_total",
            help="jitted dataflow sweeps executed",
        ).inc(executed)
        histogram(
            "dataflow_propagate_seconds",
            help="wall time of a propagate-to-fixpoint run",
        ).observe(elapsed)
        # per-edge recompute counts, by combinator kind — an edge only
        # recomputes in sweeps where it was scheduled (eligible subset
        # on the per-edge path, dirty closure on the fused path); the
        # skipped evaluations are counted too (the "work the frontier
        # saved" metric)
        by_kind: dict = {}
        skipped_by_kind: dict = {}
        for i, e in enumerate(self.edges):
            by_kind[e.kind] = by_kind.get(e.kind, 0) + runs[i]
            skipped_by_kind[e.kind] = (
                skipped_by_kind.get(e.kind, 0) + executed - runs[i]
            )
        for kind, n in by_kind.items():
            if n:
                counter(
                    "dataflow_edge_recomputes_total",
                    help="edge contribution evaluations, by combinator "
                         "kind",
                    kind=kind,
                ).inc(n)
        total_skipped = 0
        for kind, n in skipped_by_kind.items():
            if n:
                total_skipped += n
                counter(
                    "dataflow_edges_skipped_total",
                    help="edge evaluations skipped by frontier "
                         "scheduling (source set clean), by kind",
                    kind=kind,
                ).inc(n)
        # causal log: one coarse record per propagate run — the fused
        # path's record carries the per-dst changed-sweep counts (the
        # only per-round signal that survives the on-device loop); the
        # deep tier adds per-edge recompute provenance (srcs -> dst,
        # the trail `lasp_tpu trace --var` reconstructs values from)
        attrs = {
            "rounds": stats["rounds"], "sweeps": executed,
            "edges": len(self.edges), "fused": stats["fused"],
        }
        if stats["changed_by_dst"] is not None:
            attrs["changed_by_dst"] = stats["changed_by_dst"]
        tel_events.emit("propagate", **attrs)
        # flight drain: the fused window's per-sweep records — real
        # rounds in the causal log where there used to be only the
        # collapsed summary above (overwritten sweeps stay collapsed:
        # the modulo ring kept the last K only)
        flight = stats.get("flight")
        if flight is not None:
            for i, rec in enumerate(flight["records"]):
                tel_events.emit(
                    "propagate_sweep",
                    sweep=flight["overwritten"] + i,
                    changed=int(sum(rec)),
                    by_dst={
                        d: int(c)
                        for d, c in zip(flight["dst_order"], rec) if c
                    },
                    fused=True,
                )
        if total_skipped:
            tel_events.emit(
                "frontier_skip", skipped=int(total_skipped),
                sweeps=executed, edges=len(self.edges),
            )
        if tel_events.deep_enabled():
            for i, e in enumerate(self.edges):
                d = e.describe()
                tel_events.emit_deep(
                    "edge_recompute", var=d["dst"], kind=d["kind"],
                    srcs=d["srcs"], sweeps=runs[i],
                )
