"""lasp_tpu — a TPU-native framework for distributed, deterministic dataflow
programming with CRDTs, with the capabilities of the reference Erlang
framework (Lasp, see SURVEY.md) rebuilt idiomatically on JAX/XLA/Pallas.

Layer map (mirrors SURVEY.md §1, redesigned per §7):

- ``lasp_tpu.lattice`` — CRDT tensor codecs + join kernels (reference L0/L2.2)
- ``lasp_tpu.store``   — variable store, inflation-gated bind, thresholds (L1)
- ``lasp_tpu.dataflow``— monotone combinator graph as jitted round sweeps (L1)
- ``lasp_tpu.mesh``    — replication/gossip/quorum over device meshes (L2/L3)
- ``lasp_tpu.quorum``  — batched request-coordination FSMs, hinted
  handoff, ring-coverage queries (the reference's 18 gen_fsm layer, L3)
- ``lasp_tpu.aae``     — active anti-entropy: vectorized Merkle
  hashtrees, pairwise tree exchange, targeted quorum repair (riak_kv
  AAE's role)
- ``lasp_tpu.serve``   — overload-hardened serving front-end: coalescing
  ingest, vectorized threshold fan-out, admission + backpressure
- ``lasp_tpu.api``     — the public Lasp verb set (L4)
- ``lasp_tpu.programs``— distributed incremental programs (L5)
- ``lasp_tpu.ops``     — Pallas/packed kernels for the hot merge path
- ``lasp_tpu.bridge``  — Erlang↔Python backend bridge (north-star, §7.6)
- ``lasp_tpu.config``  — unified typed configuration (LASP_* env overrides)
- ``lasp_tpu.telemetry`` — metric registry, spans, Prometheus/JSONL export
- ``lasp_tpu.utils``   — interning, step-trace facade
"""

__version__ = "0.1.0"

# Lazy submodule/attribute loading (PEP 562): importing the package must
# not pull in jax — lightweight consumers (CLI --help/status, the bridge
# server parent, bench.py's never-import-jax parent) need the namespace
# without paying jax's import cost or risking any backend touch.
_SUBMODULES = frozenset({
    "aae", "api", "bridge", "chaos", "config", "dataflow", "lattice",
    "membership", "mesh", "ops", "programs", "quorum", "serve", "store",
    "telemetry", "utils",
})
_ATTRS = {
    "Session": ("api", "Session"),
    "LaspConfig": ("config", "LaspConfig"),
    "get_config": ("config", "get_config"),
}


def __getattr__(name):
    import importlib

    if name in _SUBMODULES:
        return importlib.import_module(f".{name}", __name__)
    if name in _ATTRS:
        mod, attr = _ATTRS[name]
        return getattr(importlib.import_module(f".{mod}", __name__), attr)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | _SUBMODULES | set(_ATTRS))

__all__ = [
    "LaspConfig",
    "Session",
    "aae",
    "api",
    "bridge",
    "chaos",
    "config",
    "dataflow",
    "get_config",
    "lattice",
    "mesh",
    "ops",
    "programs",
    "quorum",
    "serve",
    "store",
    "telemetry",
    "__version__",
]
