"""The quorum request FSM: vocabulary, reachability, and the two
transition implementations.

One in-flight request is the reference's coordinator FSM
(``src/lasp_update_fsm.erl:174-216``): prepare (pick the preflist) →
waiting(R) (accumulate replies) → finalize/repair → waiting_n(N) →
done/failed. Here a BATCH of requests is a struct-of-arrays —

    state     int32[B]   one of the STATE_* codes below
    coord     int32[B]   coordinator replica row
    picks     int32[B,N] the preflist (N replica rows, coordinator first)
    acks      bool [B,N] which picks have replied
    deadline  int32[B]   absolute round the current wait expires at
    need      int32[B]   client quorum (R for gets, W for puts)
    degraded  bool [B]   R-of-live degradation (first-replies of whatever
                         is reachable, the ChaosRuntime.degraded_read rule)

— and one round advances EVERY request with one jitted tensor step
(:func:`transition_batched`) over the round's reachability. Reply
semantics are mask-derived: a picked replica replies in a round iff it
is live and in the coordinator's connected component of the live-edge
graph under that round's chaos mask (:func:`components` — one labeling
per round, shared by every request; a partitioned coordinator hears
only from ITS side of the cut, exactly the degraded-read confinement
rule of ``chaos.engine``).

:func:`transition_sequential` is the per-request scalar reference: the
same transition rules applied one request at a time in submit order.
The two are asserted bit-identical (states, ack sets, fired flags)
across codecs × topologies × chaos presets by ``tests/quorum/`` and
``tools/quorum_smoke.py`` — the batched kernel is the same machine,
vectorized, never a different protocol.
"""

from __future__ import annotations

import numpy as np

# -- state vocabulary (the reference FSM's state atoms) ---------------------
PREPARE = 0    #: submitted, preflist not yet picked
WAITING_R = 1  #: execute fired, accumulating replies toward the client quorum
WAITING_N = 2  #: client answered, finalizing toward all-N acks
REPAIR = 3     #: quorum fired THIS round: value/repair/hint work executes
DONE = 4       #: terminal: answered and finalized
FAILED = 5     #: terminal: retries exhausted without a quorum

STATE_NAMES = {
    PREPARE: "prepare",
    WAITING_R: "waiting_r",
    WAITING_N: "waiting_n",
    REPAIR: "repair",
    DONE: "done",
    FAILED: "failed",
}


def preflist(coord: int, n: int, n_replicas: int) -> np.ndarray:
    """The deterministic N-row preflist of a coordinator: the ring walk
    ``[coord, coord+1, ...] mod R`` (riak_core's successor-vnode
    preflist, ``src/lasp_core.erl:231-235``). Static — liveness is
    handled by acks/timeouts, not by the pick (the reference's preflist
    is static per ring epoch too)."""
    if n > n_replicas:
        raise ValueError(
            f"preflist of {n} from a {n_replicas}-replica population"
        )
    return (int(coord) + np.arange(int(n), dtype=np.int64)) % int(n_replicas)


def next_live_coordinator(coord: int, crashed: np.ndarray) -> "int | None":
    """The re-pick rule: the first LIVE replica strictly after ``coord``
    in ring order (wrapping), or None when every replica is down.
    Deterministic — re-pick is part of the replayable protocol."""
    n = crashed.shape[0]
    for step in range(1, n + 1):
        cand = (int(coord) + step) % n
        if not crashed[cand]:
            return cand
    return None


def components(neighbors: np.ndarray, mask, live: np.ndarray) -> np.ndarray:
    """``int32[R]`` connected-component labels of the LIVE-edge graph:
    two replicas share a label iff a path of alive links (this round's
    chaos mask, both endpoints live) connects them. Labels are the
    minimum member index (deterministic). Crashed replicas keep their
    own index as label and are additionally excluded by the ``live``
    guard at every use site.

    One labeling per round serves every in-flight request — the batched
    generalization of ``ChaosRuntime._reachable_live``'s per-call BFS.
    Min-label propagation with path halving: O(E · log R) host work."""
    nbrs = np.asarray(neighbors)
    R, K = nbrs.shape
    live = np.asarray(live, dtype=bool)
    alive = np.ones((R, K), dtype=bool) if mask is None else np.asarray(
        mask, dtype=bool
    ).copy()
    alive &= live[:, None] & live[nbrs]
    rows = np.repeat(np.arange(R, dtype=np.int64), K)[alive.ravel()]
    cols = nbrs.ravel()[alive.ravel()]
    comp = np.arange(R, dtype=np.int64)
    while True:
        new = comp.copy()
        if rows.size:
            np.minimum.at(new, rows, comp[cols])
            np.minimum.at(new, cols, comp[rows])
        new = new[new]  # path halving: labels chase their own label
        if np.array_equal(new, comp):
            break
        comp = new
    return comp.astype(np.int32)


# -- the transition step ----------------------------------------------------
#
# Both implementations advance WAITING_R / WAITING_N requests one round:
#
#   reach[b,i]  = live[coord] & live[picks] & comp[picks] == comp[coord]
#   acks'       = acks | (reach & pick_valid)        (replies accumulate)
#   eff_need    = degraded ? max(1, min(need, reachable picks)) : need
#   quorum_now  = WAITING_R & popcount(acks') >= eff_need   -> REPAIR
#   timeout_now = WAITING_R & ~quorum_now & round >= deadline
#   done_now    = WAITING_N & (all valid picks acked | round >= deadline)
#
# PREPARE processing, retry/fail resolution of timeout_now, and the
# REPAIR-state join work are HOST decisions (they touch the store /
# hint log) — see engine.py. The kernel's outputs are exactly the flags
# the host needs, so one dispatch serves thousands of requests.

_BUCKET_MIN = 8


def bucket_of(n: int) -> int:
    """Pad the active-request axis to a power-of-two bucket so the
    jitted kernel recompiles O(log B) times, not per batch size (the
    frontier engine's bucket discipline)."""
    b = _BUCKET_MIN
    while b < n:
        b *= 2
    return b


def _transition_rules(xp, state, coord, picks, pick_valid, acks, deadline,
                      need, degraded, valid, comp, live, rnd):
    """THE transition rule set, written ONCE and parameterized by array
    namespace: ``xp=numpy`` serves the sequential reference and the
    host-side checks; ``xp=jax.numpy`` is what the batched kernel
    traces. Every op used (where/maximum/minimum/sum/astype/indexing)
    is API-identical across the two — a rule change lands in both
    implementations by construction, which is what keeps the
    batched-vs-sequential bit-identity contract from drifting."""
    active = valid & ((state == WAITING_R) | (state == WAITING_N))
    c_ok = live[coord]
    reach = (
        c_ok[:, None]
        & live[picks]
        & (comp[picks] == comp[coord][:, None])
        & pick_valid
    )
    new_acks = xp.where(active[:, None], acks | reach, acks)
    newly = new_acks & ~acks
    ackn = new_acks.sum(axis=1).astype(xp.int32)
    reach_n = reach.sum(axis=1).astype(xp.int32)
    n_valid = pick_valid.sum(axis=1).astype(xp.int32)
    eff_need = xp.where(
        degraded, xp.maximum(1, xp.minimum(need, reach_n)), need
    ).astype(xp.int32)
    quorum_now = valid & (state == WAITING_R) & (ackn >= eff_need)
    timeout_now = (
        valid & (state == WAITING_R) & ~quorum_now & (rnd >= deadline)
    )
    done_now = (
        valid & (state == WAITING_N) & ((ackn >= n_valid) | (rnd >= deadline))
    )
    new_state = xp.where(quorum_now, REPAIR, state)
    new_state = xp.where(done_now, DONE, new_state).astype(state.dtype)
    return new_state, new_acks, newly, quorum_now, timeout_now, done_now


_kernel_cache: dict = {}


def _batched_kernel(bucket: int, n_picks: int):
    """The jitted transition for one (bucket, N) shape — the
    "one vmapped kernel per round" of the tentpole. Cached per shape;
    shifting batch sizes reuse executables via the bucket pad."""
    key = (bucket, n_picks)
    fn = _kernel_cache.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    def step(state, coord, picks, pick_valid, acks, deadline, need,
             degraded, valid, comp, live, rnd):
        return _transition_rules(
            jnp, state, coord, picks, pick_valid, acks, deadline, need,
            degraded, valid, comp, live, rnd,
        )

    fn = jax.jit(step)
    _kernel_cache[key] = fn
    return fn


def transition_batched(state, coord, picks, pick_valid, acks, deadline,
                       need, degraded, comp, live, rnd: int):
    """Advance EVERY request one round in one device dispatch. Arrays
    are the batch's struct-of-arrays slices (numpy, length B); returns
    numpy ``(state', acks', newly, quorum_now, timeout_now, done_now)``
    — bit-identical to :func:`transition_sequential` on the same inputs
    (the smoke-tested contract)."""
    import jax.numpy as jnp

    b = state.shape[0]
    bucket = bucket_of(b)
    pad = bucket - b

    def padded(x, fill=0):
        if pad == 0:
            return jnp.asarray(x)
        return jnp.asarray(
            np.concatenate(
                [x, np.full((pad,) + x.shape[1:], fill, dtype=x.dtype)]
            )
        )

    valid = np.zeros(bucket, dtype=bool)
    valid[:b] = True
    fn = _batched_kernel(bucket, picks.shape[1])
    out = fn(
        padded(state), padded(coord), padded(picks), padded(pick_valid),
        padded(acks), padded(deadline), padded(need), padded(degraded),
        jnp.asarray(valid), jnp.asarray(comp), jnp.asarray(live),
        jnp.int32(rnd),
    )
    return tuple(np.asarray(o)[:b] for o in out)


def transition_sequential(state, coord, picks, pick_valid, acks, deadline,
                          need, degraded, comp, live, rnd: int):
    """The per-request scalar reference: identical rules, one request at
    a time (the shape of the reference's one-gen_fsm-per-request
    machine). The bit-identity oracle for :func:`transition_batched`."""
    b = state.shape[0]
    outs = [
        np.empty_like(state), acks.copy(),
        np.zeros_like(acks), np.zeros(b, dtype=bool),
        np.zeros(b, dtype=bool), np.zeros(b, dtype=bool),
    ]
    for i in range(b):
        sl = slice(i, i + 1)
        one = _transition_rules(
            np, state[sl], coord[sl], picks[sl], pick_valid[sl], acks[sl],
            deadline[sl], need[sl], degraded[sl],
            np.ones(1, dtype=bool), comp, live, rnd,
        )
        for o, v in zip(outs, one):
            o[sl] = v
    return tuple(outs)
