"""Ring-coverage queries: partition-sweep map-merge, one grouped
dispatch per plan group.

The reference's coverage execute (``src/lasp_execute_coverage_fsm.erl:
50-97``) opens a coverage plan over the ring, folds each partition's
accumulator locally (the MAP), and merges every partition's CRDT with
``Type:merge`` at the coordinator (the MERGE) before ``Type:value`` +
``Module:value``. The TPU rebuild keeps that two-phase structure —
per-shard partial joins, then a log-depth merge of the shard partials —
because associativity/commutativity of the join makes it bit-identical
to any other join schedule, and the shard phase is exactly what a
partitioned population computes device-locally.

Batching: variables sharing a mesh signature (``mesh.plan.
signature_of``) stack into ``[G, R, ...]`` super-tensors and ONE
vmapped sweep serves the whole group — the same megabatch discipline as
the gossip plan compiler, now on the query path. A store full of 2i
index views (``programs/riak_index.py`` auto-registers one OR-Set per
observed index spec — all same spec, all one group) answers every
view's coverage execute in one dispatch.
"""

from __future__ import annotations

import numpy as np

from ..mesh.gossip import join_all
from ..mesh.plan import signature_of, stack_group
from ..mesh.programs import MeshSession
from ..mesh.shard_gossip import shard_rows
from ..telemetry import counter, span

#: jitted sweep cache, keyed by (codec, spec-hashable, G, R, S)
_sweep_cache: dict = {}


def _sweep_fn(codec, spec, g: int, n_replicas: int, n_shards: int):
    """One compiled grouped partition-sweep: ``[G, R, ...]`` stacked
    populations -> ``[G, ...]`` coverage tops. Per member: S per-shard
    partial joins (the map phase; contiguous ``shard_rows`` blocks, the
    shard layout partitioned gossip ships), then one log-depth merge of
    the shard partials (the coverage-FSM merge)."""
    import jax
    import jax.numpy as jnp

    key = (codec, repr(spec), g, n_replicas, n_shards)
    fn = _sweep_cache.get(key)
    if fn is not None:
        return fn
    blocks = [
        np.asarray(shard_rows(n_replicas, n_shards, s), dtype=np.int64)
        for s in range(n_shards)
    ]

    def one(states):
        partials = []
        for rows in blocks:
            sub = jax.tree_util.tree_map(
                lambda x, r=rows: x[jnp.asarray(r)], states
            )
            partials.append(join_all(codec, spec, sub))
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *partials
        )
        return join_all(codec, spec, stacked)

    fn = jax.jit(jax.vmap(one) if g > 1 else one)
    _sweep_cache[key] = fn
    return fn


def coverage_sweep(rt, var_ids=None, n_shards: int = 4) -> dict:
    """Coverage values for ``var_ids`` (default: every variable):
    ``{var_id: decoded value}``, computed as grouped partition-sweep
    map-merges — one dispatch per plan group, not per variable. The
    result for each variable is bit-identical to
    ``rt.coverage_value(var_id)`` (any join schedule reaches the same
    top); what changes is the dispatch count."""
    import jax

    ids = list(rt.var_ids if var_ids is None else var_ids)
    for v in ids:
        rt._population(v)  # sync late declares before grouping
    n_shards = max(1, min(int(n_shards), rt.n_replicas))
    groups: dict = {}
    order: list = []
    for v in ids:
        sig = signature_of(rt, v)
        key = sig if sig is not None else ("singleton", v)
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(v)
    out: dict = {}
    with span("quorum.coverage", vars=len(ids), groups=len(order)):
        for key in order:
            members = groups[key]
            codec, spec = rt._mesh_meta(members[0])
            fn = _sweep_fn(codec, spec, len(members), rt.n_replicas,
                           n_shards)
            if len(members) == 1:
                tops = [fn(rt._population(members[0]))]
            else:
                stacked = stack_group(
                    [rt._population(v) for v in members]
                )
                stacked_tops = fn(stacked)
                tops = [
                    jax.tree_util.tree_map(lambda x, _i=i: x[_i],
                                           stacked_tops)
                    for i in range(len(members))
                ]
            for v, top in zip(members, tops):
                var = rt.store.variable(v)
                out[v] = rt.store._decode_value(
                    var, rt._to_dense_row(v, top)
                )
    counter(
        "quorum_coverage_queries_total",
        help="grouped ring-coverage sweeps executed (one count per "
             "sweep call, any number of variables)",
    ).inc()
    return out


class _CoverageSession(MeshSession):
    """A MeshSession whose coverage reads serve from a precomputed
    grouped sweep — programs' ``execute`` callbacks read their
    accumulator without re-dispatching one join per program."""

    def __init__(self, runtime, values: dict):
        super().__init__(runtime)
        self._values = values

    def value(self, var_id: str):
        if self.replica is None and self.quorum is None:
            if var_id in self._values:
                return self._values[var_id]
        return super().value(var_id)


def ring_coverage_execute(rt, names=None, n_shards: int = 4) -> dict:
    """Coverage-execute every named program (default: all registered)
    against ONE grouped partition sweep: ``{name: program value}``.
    This is the reference's ``execute(global)`` fan-out — every 2i
    index view merged over the ring — collapsed to one dispatch per
    plan group (all same-spec OR-Set views share a single stacked
    sweep). Results are bit-identical to per-program
    ``rt.execute(name)``."""
    programs = rt.programs
    names = list(programs if names is None else names)
    missing = [n for n in names if n not in programs]
    if missing:
        raise KeyError(f"unknown program(s) {missing!r}")
    acc_ids = [
        programs[n].id for n in names
        if getattr(programs[n], "id", None) is not None
    ]
    values = coverage_sweep(rt, acc_ids, n_shards=n_shards) if acc_ids \
        else {}
    session = _CoverageSession(rt, values)
    out = {}
    for n in names:
        program = programs[n]
        out[n] = program.value(program.execute(session))
    return out
