"""QuorumRuntime: thousands of in-flight quorum get/put requests as
batched tensor steps over a chaos-masked gossip population.

The execution model (one :meth:`QuorumRuntime.step` = one logical
round):

1. the wrapped :class:`~lasp_tpu.chaos.engine.ChaosRuntime` runs one
   chaos round (crash/restore actions, mask compile, the runtime's own
   gossip step) — coordination RIDES the mesh, it never stalls it
   (Tascade's barrier-free discipline);
2. rows restored this round take HINTED HANDOFF first: every acked put
   whose preflist names them replays from the durable hint log
   (:mod:`.hints`) before they serve another quorum;
3. PREPARE requests pick their preflist (coordinator-first ring walk;
   a crashed coordinator routes to the next live replica) and puts
   apply their op at the coordinator row;
4. ONE jitted transition kernel advances every waiting request against
   this round's reachability (``fsm.components`` over the chaos mask):
   replies accumulate, quorums fire, timeouts flag;
5. fired requests resolve host-side in request order — get values are
   masked partial joins over the acked rows (``gossip.quorum_read``),
   READ-REPAIR and put replication collect as join contributions; a
   timeout with retries left RE-PICKS the coordinator (next live
   replica, fresh preflist, reset acks), without retries it FAILS with
   the partial-quorum error surface;
6. collected contributions land as masked partial joins
   (``ReplicatedRuntime.join_rows``), two-phase: every value read this
   round sees the PRE-resolution population (the bulk-synchronous
   Jacobi discipline of the dataflow sweeps), then all writes join in —
   join commutativity/idempotence makes the batched scatter
   bit-identical to applying each request's writes one at a time.

Read semantics vs the reference: ``lasp_read_fsm`` merges the first R
REPLY PAYLOADS as they arrive; the tensor build re-reads each acked
row at the merge round (replies are "late-merged"). Every read is
still a join of a replica subset — a monotone lower bound of the
coverage value, at least as fresh as the reference's buffered replies
(CRDT reads have no freshness ceiling to violate).

``engine="sequential"`` runs the SAME protocol one request at a time
with scalar transitions (``fsm.transition_sequential``) and
per-request joins — the oracle ``tools/quorum_smoke.py`` and
``tests/quorum/`` assert the batched engine bit-identical against:
results, repair writes, ack sequences, final population states.
"""

from __future__ import annotations

import numpy as np

from ..mesh.gossip import quorum_read, rows_traffic_bytes
from ..membership.errors import StaleEpochError
from ..telemetry import counter, events as tel_events, gauge, histogram, span
from ..telemetry.convergence import get_monitor
from ..utils.metrics import Timer
from . import fsm
from .hints import HintLog


class PartialQuorumError(RuntimeError):
    """A request exhausted its retries without assembling its quorum —
    the reference FSM's ``{error, timeout}`` reply surface. Raised by
    :meth:`QuorumRuntime.result` for FAILED requests (the failure is
    also readable non-raising via ``result(rid, raise_on_error=False)``)."""


class _Request:
    """Host-side record of one request (the python fields that never
    enter the kernel: op payloads, results, latency stamps)."""

    __slots__ = (
        "rid", "kind", "var", "op", "actor", "n", "need", "timeout",
        "retries_left", "degraded", "repair", "put_row", "applied_row",
        "submit_round",
        "ack_round", "final_round", "status", "value", "error",
        "repaired_rows", "pushed_rows", "retries_used",
    )

    def __init__(self, rid, kind, var, op, actor, n, need, timeout,
                 retries, degraded, repair):
        self.rid = rid
        self.kind = kind
        self.var = var
        self.op = op
        self.actor = actor
        self.n = int(n)
        self.need = int(need)
        self.timeout = int(timeout)
        self.retries_left = int(retries)
        self.retries_used = 0
        self.degraded = bool(degraded)
        self.repair = bool(repair)
        self.put_row = None
        #: the replica row update_at applied the op at — the ONE row
        #: that holds the write before any push; a re-picked coordinator
        #: is NOT this row and must receive the delta like any pick
        self.applied_row = None
        self.submit_round = None
        self.ack_round = None
        self.final_round = None
        self.status = "pending"
        self.value = None
        self.error = None
        self.repaired_rows = 0
        self.pushed_rows = 0


class QuorumRuntime:
    """One population + one fault timeline + a batch of coordination
    FSMs; see the module doc.

    ``runtime`` is a :class:`~lasp_tpu.chaos.engine.ChaosRuntime`, or a
    bare :class:`~lasp_tpu.mesh.runtime.ReplicatedRuntime` (wrapped in a
    fault-free chaos timeline so the stepping/mask plumbing is uniform).
    ``n``/``r``/``w`` default to the reference's N=3, R=W=2;
    ``engine`` picks the batched kernel (default) or the sequential
    per-request reference; ``hints`` is a :class:`HintLog`, a path for a
    durable one, or None for in-memory."""

    def __init__(self, runtime, *, n: int = 3, r: int = 2, w: int = 2,
                 timeout: int = 4, retries: int = 1,
                 engine: str = "batched",
                 hints: "HintLog | str | None" = None,
                 mode: str = "dense"):
        from ..chaos.engine import ChaosRuntime
        from ..chaos.schedule import ChaosSchedule

        if not isinstance(runtime, ChaosRuntime):
            schedule = ChaosSchedule(
                runtime.n_replicas, runtime._host_neighbors, events=()
            )
            runtime = ChaosRuntime(runtime, schedule)
        self.ch = runtime
        self.rt = runtime.rt
        if engine not in ("batched", "sequential"):
            raise ValueError(
                f"unknown engine {engine!r} (batched | sequential)"
            )
        self.engine = engine
        self.mode = mode
        self.n_default = int(n)
        self.r_default = int(r)
        self.w_default = int(w)
        self.timeout_default = int(timeout)
        self.retries_default = int(retries)
        if isinstance(hints, str):
            hints = HintLog(hints)
        self.hints = hints if hints is not None else HintLog()
        R = self.rt.n_replicas
        if self.n_default > R:
            raise ValueError(f"n={n} exceeds the {R}-replica population")
        #: widest preflist any request may use (the kernel's pick axis)
        self.n_max = self.n_default
        self._reqs: dict = {}
        self._order: list = []  # rids in submit order (the batch axis)
        #: non-terminal rids in submit order — per-round work is
        #: O(inflight), never O(requests-ever) (long-lived serving runs
        #: retire requests every round; result/_reqs stay queryable)
        self._active: list = []
        self._next_rid = 0
        # struct-of-arrays control plane (grown on demand)
        self._cap = 0
        self._state = np.zeros(0, dtype=np.int32)
        self._coord = np.zeros(0, dtype=np.int32)
        self._picks = np.zeros((0, self.n_max), dtype=np.int32)
        self._pick_valid = np.zeros((0, self.n_max), dtype=bool)
        self._acks = np.zeros((0, self.n_max), dtype=bool)
        self._deadline = np.zeros(0, dtype=np.int32)
        self._need = np.zeros(0, dtype=np.int32)
        self._degraded = np.zeros(0, dtype=bool)
        #: (round, rid, event, payload) protocol trace — the ack-sequence
        #: record the bit-identity assertions compare across engines
        self.trace: list = []
        self._comp_cache: "tuple | None" = None
        #: the membership epoch the live picks/preflists were minted
        #: under — a runtime resize/staged commit advances the
        #: runtime's epoch and every in-flight request FENCES
        #: (:meth:`_epoch_fence`): re-prepare against the new ring with
        #: a retry budget, typed ``StaleEpochError`` without one.
        #: Without the fence a request would keep preflist indices whose
        #: meaning changed (runtime.py ``quorum_value``: a stale index
        #: after a resize silently reads the wrong quorum).
        self._fence_epoch = self.rt.membership_epoch
        # aggregate accounting (the report / bench surface)
        self.completed = 0
        self.failed = 0
        self.retries = 0
        self.repaired_rows = 0
        self.pushed_rows = 0
        self.wire_bytes = 0
        #: terms acked to clients, by var — the no-acknowledged-write-
        #: lost invariant's witness set (chaos.invariants.check_no_write_lost)
        self.acked_terms: dict = {}

    # -- submission -----------------------------------------------------------
    def _grow(self, need: int) -> None:
        if need <= self._cap:
            return
        cap = max(16, self._cap)
        while cap < need:
            cap *= 2
        pad = cap - self._cap

        def ext(a, fill=0):
            return np.concatenate(
                [a, np.full((pad,) + a.shape[1:], fill, dtype=a.dtype)]
            )

        self._state = ext(self._state, fsm.DONE)
        self._coord = ext(self._coord)
        self._picks = ext(self._picks)
        self._pick_valid = ext(self._pick_valid, False)
        self._acks = ext(self._acks, False)
        self._deadline = ext(self._deadline)
        self._need = ext(self._need)
        self._degraded = ext(self._degraded, False)
        self._cap = cap

    def _submit(self, kind, var, op, actor, coordinator, n, need, timeout,
                retries, degraded, repair) -> int:
        if var not in self.rt.store.ids():
            raise KeyError(var)
        self.rt._population(var)  # sync late declares before any quorum
        R = self.rt.n_replicas
        n = self.n_default if n is None else int(n)
        if not 1 <= n <= min(self.n_max, R):
            raise ValueError(
                f"n={n} outside [1, {min(self.n_max, R)}] (n_max is fixed "
                "at construction — the kernel's pick axis)"
            )
        if not 1 <= need <= n:
            raise ValueError(f"quorum {need} outside [1, n={n}]")
        coordinator = 0 if coordinator is None else int(coordinator)
        if not 0 <= coordinator < R:
            raise IndexError(
                f"coordinator {coordinator} out of range for {R} replicas"
            )
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(rid, kind, var, op, actor, n, need, timeout,
                       retries, degraded, repair)
        req.submit_round = self.ch.round
        self._reqs[rid] = req
        self._order.append(rid)
        self._active.append(rid)
        self._grow(rid + 1)
        self._state[rid] = fsm.PREPARE
        self._coord[rid] = coordinator
        self._need[rid] = int(need)
        self._degraded[rid] = bool(degraded)
        counter(
            "quorum_requests_total",
            help="quorum coordination requests submitted, by kind",
            kind=kind,
        ).inc()
        return rid

    def submit_get(self, var_id: str, coordinator: "int | None" = None, *,
                   r: "int | None" = None, n: "int | None" = None,
                   timeout: "int | None" = None,
                   retries: "int | None" = None,
                   degraded: bool = False, repair: bool = True) -> int:
        """Enqueue one quorum GET (the read FSM): answered once R of the
        N preflist rows reply; the value is their join. ``degraded=True``
        applies the R-of-live rule (answer from whatever is reachable,
        the ``ChaosRuntime.degraded_read`` contract) instead of failing
        on a partial quorum. Returns the request id."""
        return self._submit(
            "get", var_id, None, None, coordinator, n,
            self.r_default if r is None else int(r),
            self.timeout_default if timeout is None else int(timeout),
            self.retries_default if retries is None else int(retries),
            degraded, repair,
        )

    def submit_put(self, var_id: str, op: tuple, actor,
                   coordinator: "int | None" = None, *,
                   w: "int | None" = None, n: "int | None" = None,
                   timeout: "int | None" = None,
                   retries: "int | None" = None) -> int:
        """Enqueue one quorum PUT (the update FSM): the op applies at
        the coordinator row, replicates to the N preflist rows as
        masked partial joins, and acks to the client at W replicas —
        at which point the write lands in the durable hint log (the
        no-acknowledged-write-lost contract). Returns the request id."""
        return self._submit(
            "put", var_id, tuple(op), actor, coordinator, n,
            self.w_default if w is None else int(w),
            self.timeout_default if timeout is None else int(timeout),
            self.retries_default if retries is None else int(retries),
            False, False,
        )

    # -- stepping -------------------------------------------------------------
    def active_rids(self) -> list:
        return [
            rid for rid in self._active
            if self._state[rid] not in (fsm.DONE, fsm.FAILED)
        ]

    @property
    def inflight(self) -> int:
        return len(self.active_rids())

    def _components(self, rnd: int) -> np.ndarray:
        mask = self.ch.schedule.mask_at(rnd)
        key = (id(mask), self.ch.crashed.tobytes())
        cached = self._comp_cache
        if cached is not None and cached[0] == key and cached[1] is mask:
            return cached[2]
        comp = fsm.components(
            self.rt._host_neighbors, mask, ~self.ch.crashed
        )
        self._comp_cache = (key, mask, comp)
        return comp

    def _prepare_batch(self, rnd: int) -> None:
        """PREPARE → WAITING_R for every pending request in ONE pass:
        preflists pick per request (a crashed coordinator routes to the
        next live replica first — the riak_core routing), then ALL
        puts' coordinator deltas mint through one grouped ingest cycle
        (``ReplicatedRuntime.ingest_cycle`` / ``mesh.ingest``: one
        vmapped dispatch per dispatch-plan group instead of one
        ``update_at`` per put) and their minted rows gather in one
        batched pull per variable.

        Puts hitting the SAME (var, coordinator row) in one round mint
        in sequential WAVES: each put's recorded delta row must reflect
        exactly the ops at or before it (the per-op gather contract —
        a later same-row put's delta must not widen an earlier put's
        pushes), so duplicate-row rounds degrade gracefully toward the
        sequential path; the common unique-row round is one wave.

        Mint failures keep their request in PREPARE (retried — and
        re-raised — next round) and re-raise after the round's other
        mints issue: an applied mint MUST transition, or its retry
        would double-apply. The only deviation from the historical
        per-request loop is that a mint error no longer blocks OTHER
        variables' puts submitted after it in the same round."""
        prep = [
            rid for rid in self._active
            if self._state[rid] == fsm.PREPARE
        ]
        staged: list = []  # (rid, coord, picks)
        for rid in prep:
            req = self._reqs[rid]
            coord = int(self._coord[rid])
            if self.ch.crashed[coord]:
                nxt = fsm.next_live_coordinator(coord, self.ch.crashed)
                if nxt is None:
                    self._fail(rid, rnd, "no live replica to coordinate")
                    continue
                coord = nxt
                self._coord[rid] = coord
            picks = fsm.preflist(coord, req.n, self.rt.n_replicas)
            self._picks[rid, : req.n] = picks
            self._picks[rid, req.n:] = 0
            self._pick_valid[rid] = False
            self._pick_valid[rid, : req.n] = True
            self._acks[rid] = False
            self._deadline[rid] = rnd + req.timeout
            staged.append((rid, coord, picks))
        # wave assignment: occurrence index of (var, coord-row) this round
        waves: list = []
        occurrence: dict = {}
        need_mint: set = set()
        for rid, coord, _picks in staged:
            req = self._reqs[rid]
            if req.kind == "put" and req.put_row is None:
                need_mint.add(rid)
                key = (req.var, coord)
                w = occurrence.get(key, 0)
                occurrence[key] = w + 1
                while len(waves) <= w:
                    waves.append({})
                waves[w].setdefault(req.var, []).append(rid)
        minted: set = set()
        mint_exc = None
        for wave in waves:
            if mint_exc is not None:
                break  # unminted requests stay PREPARE and retry
            batches = {
                var: [
                    (int(self._coord[rid]), self._reqs[rid].op,
                     self._reqs[rid].actor)
                    for rid in rids
                ]
                for var, rids in wave.items()
            }
            report = self.rt.ingest_cycle(batches, isolate_errors=True)
            import jax

            for var, rids in wave.items():
                exc = report["errors"].get(var)
                applied = len(rids)
                if exc is not None:
                    # sequential prefix semantics: ops before the
                    # failure applied (batch_index marks the boundary;
                    # a batch-level error applied nothing)
                    applied = min(
                        int(getattr(exc, "batch_index", 0)), len(rids)
                    )
                    if mint_exc is None:
                        mint_exc = exc
                if not applied:
                    continue
                pop = self.rt._population(var)
                rows = np.asarray(
                    [int(self._coord[rid]) for rid in rids[:applied]],
                    dtype=np.int64,
                )
                got = jax.tree_util.tree_map(lambda x: x[rows], pop)
                for i, rid in enumerate(rids[:applied]):
                    req = self._reqs[rid]
                    req.put_row = jax.tree_util.tree_map(
                        lambda x, _i=i: x[_i], got
                    )
                    req.applied_row = int(self._coord[rid])
                    minted.add(rid)
        for rid, coord, picks in staged:
            if rid in need_mint and rid not in minted:
                continue  # mint failed/aborted: stays PREPARE, retries
            self._state[rid] = fsm.WAITING_R
            self.trace.append((rnd, rid, "issue", (coord, picks.tolist())))
        if mint_exc is not None:
            raise mint_exc

    def _epoch_fence(self, rnd: int) -> None:
        """The membership epoch advanced under this batch's feet —
        riak_core's ``{error, ring_changed}`` surface, typed. A request
        fences only when the change actually INVALIDATED it: its
        coordinator or a valid pick no longer exists, or its preflist
        width no longer fits the population. Surviving rows keep their
        indices in this membership model, so a pure GROW (and a shrink
        that spares the whole preflist) leaves in-flight requests
        untouched — no spurious retry burn, no dropped phase-B pushes
        at still-valid replicas.

        For AFFECTED requests:

        - WAITING_N finalizes: the client already has its answer;
          chasing straggler acks at departed rows would push state at
          rows that no longer exist;
        - WAITING_R with retries left RE-PREPARES against the new ring
          (one retry consumed): a departed coordinator routes to its
          ring-fold claim successor, acks reset, and a put's
          already-minted delta rides to the fresh picks (a mint at a
          departed row was handed to the claim successor by the staged
          transfer/graceful merge, so nothing re-applies);
        - WAITING_R without retries (or a preflist width the population
          can no longer hold) FAILS with the typed ``stale_epoch``
          status — :meth:`result` raises
          :class:`~lasp_tpu.membership.errors.StaleEpochError`."""
        cur = self.rt.membership_epoch
        prev = self._fence_epoch
        self._fence_epoch = cur
        R = self.rt.n_replicas
        refenced = failed = 0
        for rid in list(self._active):
            st = self._state[rid]
            req = self._reqs[rid]
            if st == fsm.PREPARE:
                # not yet issued: nothing stale in flight, but a staged
                # coordinator index may have departed — remap to its
                # claim successor before the preflist pick. A preflist
                # width the shrunken population can no longer hold must
                # fail typed HERE: _prepare_batch's pick would raise an
                # untyped ValueError and strand the whole step
                if req.n > R:
                    self._fence_fail(rid, req, rnd, prev, cur, R)
                    failed += 1
                    continue
                coord = int(self._coord[rid])
                if coord >= R:
                    self._coord[rid] = coord % R
                    self.trace.append(
                        (rnd, rid, "epoch_fence", ("remapped", cur))
                    )
                continue
            if st not in (fsm.WAITING_R, fsm.WAITING_N):
                continue
            affected = (
                req.n > R
                or int(self._coord[rid]) >= R
                or bool(
                    (self._picks[rid][self._pick_valid[rid]] >= R).any()
                )
            )
            if not affected:
                continue
            if st == fsm.WAITING_N:
                self._finalize(rid, rnd)
                self.trace.append(
                    (rnd, rid, "epoch_fence", ("finalized", cur))
                )
                continue
            if req.n <= R and req.retries_left > 0:
                req.retries_left -= 1
                req.retries_used += 1
                self.retries += 1
                coord = int(self._coord[rid])
                if coord >= R:
                    coord = coord % R  # the claim successor's row
                self._coord[rid] = coord
                self._acks[rid] = False
                self._state[rid] = fsm.PREPARE
                if req.applied_row is not None and req.applied_row >= R:
                    # the mint row departed: every fresh pick must
                    # receive the delta (the claim successor holds the
                    # handed-off tokens, and re-joining is idempotent)
                    req.applied_row = -1
                refenced += 1
                self.trace.append(
                    (rnd, rid, "epoch_fence", ("refenced", cur))
                )
            else:
                self._fence_fail(rid, req, rnd, prev, cur, R)
                failed += 1
        if refenced:
            counter(
                "quorum_epoch_fences_total",
                help="in-flight quorum requests fenced by a membership "
                     "epoch change, by outcome (refenced = re-prepared "
                     "on the new ring, failed = typed StaleEpochError)",
                outcome="refenced",
            ).inc(refenced)
        if failed:
            counter(
                "quorum_epoch_fences_total",
                help="in-flight quorum requests fenced by a membership "
                     "epoch change, by outcome (refenced = re-prepared "
                     "on the new ring, failed = typed StaleEpochError)",
                outcome="failed",
            ).inc(failed)
        self._active = [
            rid for rid in self._active
            if self._state[rid] not in (fsm.DONE, fsm.FAILED)
        ]

    def _fence_fail(self, rid, req, rnd: int, prev: int, cur: int,
                    R: int) -> None:
        """Resolve one fenced request as typed ``stale_epoch`` (the
        shared terminal arm of :meth:`_epoch_fence`)."""
        self._state[rid] = fsm.FAILED
        req.status = "stale_epoch"
        req.error = (
            f"membership epoch advanced {prev} -> {cur} mid-flight "
            f"(population now {R} replicas"
            + (f", below the request's preflist width n={req.n}"
               if req.n > R else "")
            + ") and no retry can fit it — re-submit against the "
            "current ring"
        )
        req.final_round = rnd
        self.failed += 1
        counter(
            "quorum_completions_total",
            help="quorum requests resolved, by kind and outcome",
            kind=req.kind, outcome="stale_epoch",
        ).inc()
        self.trace.append((rnd, rid, "epoch_fence", ("failed", cur)))

    def _fail(self, rid: int, rnd: int, why: str) -> None:
        req = self._reqs[rid]
        self._state[rid] = fsm.FAILED
        req.status = "failed"
        req.error = why
        req.final_round = rnd
        self.failed += 1
        counter(
            "quorum_completions_total",
            help="quorum requests resolved, by kind and outcome",
            kind=req.kind, outcome="failed",
        ).inc()
        self.trace.append((rnd, rid, "failed", why))

    def _repick(self, rid: int, rnd: int) -> None:
        """Timeout with retries left: coordinator re-pick — the next
        LIVE replica in ring order takes over with a fresh preflist and
        empty ack set (a put's row delta is already minted and joins at
        the new picks as they ack)."""
        req = self._reqs[rid]
        req.retries_left -= 1
        req.retries_used += 1
        self.retries += 1
        counter(
            "quorum_retries_total",
            help="quorum coordinator re-picks after a wait timeout",
        ).inc()
        nxt = fsm.next_live_coordinator(int(self._coord[rid]),
                                        self.ch.crashed)
        if nxt is None:
            self._fail(rid, rnd, "no live replica to coordinate")
            return
        self._coord[rid] = nxt
        picks = fsm.preflist(nxt, req.n, self.rt.n_replicas)
        self._picks[rid, : req.n] = picks
        self._acks[rid] = False
        self._deadline[rid] = rnd + req.timeout
        self._state[rid] = fsm.WAITING_R
        self.trace.append((rnd, rid, "repick", (nxt, picks.tolist())))

    def _record_ack_terms(self, req) -> None:
        """Witness terms for the no-acknowledged-write-lost invariant:
        the terms a client was told are durable (set-family adds; other
        op shapes are covered by the hint log + bit-equality checks,
        not by term membership)."""
        op = req.op
        terms = ()
        if op[0] == "add":
            terms = (op[1],)
        elif op[0] == "add_all":
            terms = tuple(op[1])
        elif op[0] == "add_by_token" and len(op) >= 3:
            terms = (op[2],)
        if terms:
            self.acked_terms.setdefault(req.var, set()).update(terms)

    def step(self) -> dict:
        """ONE logical round: chaos/gossip step, hinted handoff for
        restored rows, then the FSM batch advance (see the module doc).
        Returns ``{"round", "residual", "fired", "failed", "pushed",
        "repaired"}`` for the round."""
        rnd = self.ch.round
        self.ch.sync_membership()
        if self.rt.membership_epoch != self._fence_epoch:
            self._epoch_fence(rnd)
        residual = self.ch.step(mode=self.mode)
        for replica in self.ch.last_restored:
            handed = self.hints.replay(self.rt, replica)
            # post-replay reclaim: records this restore just re-acked
            # at FULL preflist strength stop accumulating across
            # repeat crashes (records still short of N live holders
            # stay load-bearing — the no-write-lost contract)
            pruned = self.hints.prune_replayed(
                self.rt, replica, live=~self.ch.crashed
            )
            self.trace.append(
                (rnd, -1, "handoff", (int(replica), handed, pruned))
            )
            tel_events.emit(
                "quorum", replica=int(replica), action="hinted_handoff",
                rows=handed, pruned=pruned, round=rnd,
            )
        with span("quorum.step", round=rnd):
            out = self._fsm_step(rnd)
        gauge(
            "quorum_inflight",
            help="quorum requests currently in flight (non-terminal FSMs)",
        ).set(self.inflight)
        return {"round": rnd, "residual": int(residual), **out}

    def _fsm_step(self, rnd: int) -> dict:
        # PREPARE processing first: a request submitted before this round
        # issues now, so this round's reachability already counts replies
        # (put mints ride one grouped ingest dispatch per plan group)
        self._prepare_batch(rnd)
        active = [
            rid for rid in self._active
            if self._state[rid] in (fsm.WAITING_R, fsm.WAITING_N)
        ]
        fired = failed = 0
        pushes: list = []   # (var, row, contrib_tree) put replication
        repairs: list = []  # (var, row, contrib_tree) read-repair
        if active:
            idx = np.asarray(active, dtype=np.int64)
            comp = self._components(rnd)
            live = ~self.ch.crashed
            args = (
                self._state[idx], self._coord[idx], self._picks[idx],
                self._pick_valid[idx], self._acks[idx],
                self._deadline[idx], self._need[idx], self._degraded[idx],
                comp, live, rnd,
            )
            with Timer() as t:
                if self.engine == "batched":
                    (new_state, new_acks, newly, quorum_now, timeout_now,
                     done_now) = fsm.transition_batched(*args)
                else:
                    (new_state, new_acks, newly, quorum_now, timeout_now,
                     done_now) = fsm.transition_sequential(*args)
            self._ledger_record(len(active), t.elapsed)
            self._state[idx] = new_state
            self._acks[idx] = new_acks
            # -- host resolution, rid order (both engines identical) ----
            # phase A reads all use the PRE-resolution population
            values: dict = {}
            for k, rid in enumerate(active):
                req = self._reqs[rid]
                ack_rows = self._picks[rid][
                    self._pick_valid[rid] & self._acks[rid]
                ]
                if newly[k].any():
                    new_rows = sorted(
                        int(r) for r in self._picks[rid][newly[k]]
                    )
                    self.trace.append((rnd, rid, "ack", new_rows))
                    if req.kind == "put":
                        for r in new_rows:
                            # exclude only the row the op APPLIED at: a
                            # RE-PICKED coordinator acks like any pick
                            # and must receive the delta, or it would
                            # count toward W while holding nothing
                            if r != req.applied_row:
                                pushes.append((req.var, r, req.put_row))
                                req.pushed_rows += 1
                if quorum_now[k]:
                    fired += 1
                    req.ack_round = rnd
                    if req.kind == "get":
                        values[rid] = self._get_value(req, ack_rows)
                        if req.repair:
                            reach = (
                                live[self._picks[rid]]
                                & (comp[self._picks[rid]]
                                   == comp[self._coord[rid]])
                                & self._pick_valid[rid]
                            )
                            top = values[rid][1]
                            for r in self._picks[rid][
                                self._acks[rid] & reach
                            ]:
                                repairs.append((req.var, int(r), top))
                    else:
                        self._record_ack_terms(req)
                        self.hints.append(
                            req.var,
                            self._picks[rid][self._pick_valid[rid]],
                            req.put_row, rid,
                        )
                    self.trace.append(
                        (rnd, rid, "quorum", sorted(map(int, ack_rows)))
                    )
                elif timeout_now[k]:
                    if req.retries_left > 0:
                        self._repick(rid, rnd)
                    else:
                        failed += 1
                        self._fail(
                            rid, rnd,
                            f"partial quorum: {int(self._acks[rid].sum())}"
                            f"/{req.need} replies before the deadline",
                        )
                elif done_now[k]:
                    self._finalize(rid, rnd)
            # REPAIR resolves within the round: client answered, then
            # finalize or keep waiting for the stragglers
            for k, rid in enumerate(active):
                if not quorum_now[k]:
                    continue
                req = self._reqs[rid]
                if req.kind == "get":
                    req.value = values[rid][0]
                ackn = int(self._acks[rid].sum())
                histogram(
                    "quorum_latency_rounds",
                    help="rounds from submit to client quorum, by kind",
                    kind=req.kind,
                    buckets=(1, 2, 4, 8, 16, 32, 64),
                ).observe(max(1, rnd - req.submit_round + 1))
                if ackn >= req.n:
                    self._finalize(rid, rnd)
                else:
                    self._state[rid] = fsm.WAITING_N
                    self._deadline[rid] = rnd + req.timeout
            # phase B: all writes join in (order-free by commutativity)
            pushed = self._apply_contribs(pushes, "push")
            repaired = self._apply_contribs(repairs, "repair")
            self.repaired_rows += repaired
        else:
            pushed = repaired = 0
        self._active = [
            rid for rid in self._active
            if self._state[rid] not in (fsm.DONE, fsm.FAILED)
        ]
        if fired or failed or pushed or repaired:
            tel_events.emit(
                "quorum", round=rnd, action="round",
                fired=fired, failed=failed, pushed=pushed,
                repaired=repaired, inflight=self.inflight,
            )
        return {
            "fired": fired, "failed": failed,
            "pushed": pushed, "repaired": repaired,
        }

    def _get_value(self, req, ack_rows) -> tuple:
        """(decoded value, wire top) of a get over its acked rows — a
        masked partial join via ``gossip.quorum_read`` (phase A: reads
        the pre-resolution population)."""
        pop = self.rt._population(req.var)
        var = self.rt.store.variable(req.var)
        codec, spec = self.rt._mesh_meta(req.var)
        rows = np.asarray(ack_rows, dtype=np.int64)
        top = quorum_read(codec, spec, pop, rows)
        decoded = self.rt.store._decode_value(
            var, self.rt._to_dense_row(req.var, top)
        )
        return decoded, top

    def _finalize(self, rid: int, rnd: int) -> None:
        req = self._reqs[rid]
        self._state[rid] = fsm.DONE
        req.status = "done"
        req.final_round = rnd
        if req.ack_round is None:  # all-N quorum: ack == finalize
            req.ack_round = rnd
        self.completed += 1
        counter(
            "quorum_completions_total",
            help="quorum requests resolved, by kind and outcome",
            kind=req.kind, outcome="done",
        ).inc()
        self.trace.append(
            (rnd, rid, "done", int(self._acks[rid].sum()))
        )

    def _apply_contribs(self, contribs: list, what: str) -> int:
        """Phase-B scatter: fold same-row contributions (request order)
        and join once per (var, row) — ``ReplicatedRuntime.join_rows``.
        The sequential engine applies per request instead; joins
        commute, so both land bit-identical states. Returns — and
        accounts — FRAMES (one per contribution): the wire unit, and
        the one count that is engine-independent by construction
        (whether a frame's join changed its row depends on fold order
        when several requests push one row; the device-level change
        signal stays visible via the frontier/residual)."""
        if not contribs:
            return 0
        if self.engine == "sequential":
            for var, row, tree in contribs:
                self.rt.join_rows(
                    var, np.asarray([row], dtype=np.int64), [tree]
                )
        else:
            by_var: dict = {}
            for var, row, tree in contribs:
                by_var.setdefault(var, {}).setdefault(row, []).append(tree)
            for var, rows_map in by_var.items():
                codec, spec = self.rt._mesh_meta(var)
                rows, folded = [], []
                for row in sorted(rows_map):
                    trees = rows_map[row]
                    acc = trees[0]
                    for t2 in trees[1:]:
                        acc = codec.merge(spec, acc, t2)
                    rows.append(row)
                    folded.append(acc)
                self.rt.join_rows(
                    var, np.asarray(rows, dtype=np.int64), folded
                )
        # every contribution is one row frame on the wire regardless of
        # whether the join changed the row (the frame is still sent) —
        # same accounting in both engines; per-VAR row bytes computed
        # once and multiplied by that var's frame count
        frames_per_var: dict = {}
        for v, _r, _t in contribs:
            frames_per_var[v] = frames_per_var.get(v, 0) + 1
        frame_bytes = sum(
            rows_traffic_bytes(self.rt._population(v), n)
            for v, n in frames_per_var.items()
        )
        self.wire_bytes += frame_bytes
        if what == "push":
            self.pushed_rows += len(contribs)
        counter(
            "quorum_wire_bytes_total",
            help="bytes moved by quorum coordination partial joins, by "
                 "kind (put replication pushes vs read-repair)",
            kind=what,
        ).inc(frame_bytes)
        return len(contribs)

    def _ledger_record(self, b_active: int, seconds: float) -> None:
        """One FSM-step dispatch into the kernel cost ledger — the
        ``quorum_step`` family (control-plane traffic: the struct-of-
        arrays slices + the shared component labeling)."""
        from ..telemetry import get_ledger
        from ..telemetry import registry as _reg

        if not _reg.enabled():
            return
        get_ledger().record(
            "quorum_step",
            "fsm" if self.engine == "batched" else "fsm_seq",
            n_replicas=self.rt.n_replicas,
            fanout=self.n_max,
            seconds=seconds,
            rows=fsm.bucket_of(b_active),
        )

    # -- driving / results ----------------------------------------------------
    def drain(self, max_rounds: int = 4096) -> dict:
        """Step until every submitted request resolved (and the fault
        timeline's horizon passed). Returns the :meth:`report`."""
        start = self.ch.round
        while self.inflight or self.ch.round <= self.ch.schedule.horizon:
            if self.ch.round - start >= max_rounds:
                raise RuntimeError(
                    f"quorum drain did not resolve {self.inflight} "
                    f"request(s) within {max_rounds} rounds"
                )
            self.step()
        return self.report()

    def result(self, rid: int, raise_on_error: bool = True) -> dict:
        """One request's outcome: ``{"status", "value", "rounds",
        "acks", "coordinator", "retries", "error"}``. ``rounds`` is the
        client-visible latency in logical rounds (submit → quorum).
        FAILED requests raise :class:`PartialQuorumError` unless
        ``raise_on_error=False``."""
        req = self._reqs[rid]
        if req.status == "stale_epoch" and raise_on_error:
            raise StaleEpochError(
                f"request {rid} ({req.kind} {req.var!r}): {req.error}",
                current_epoch=self.rt.membership_epoch,
            )
        if req.status == "failed" and raise_on_error:
            raise PartialQuorumError(
                f"request {rid} ({req.kind} {req.var!r}): {req.error}"
            )
        status = req.status
        if status == "pending" and req.ack_round is not None:
            # the client already has its answer; the FSM is in
            # waiting_n finalizing toward the full preflist
            status = "acked"
        return {
            "status": status,
            "kind": req.kind,
            "var": req.var,
            "value": req.value,
            "rounds": (
                None if req.ack_round is None
                else max(1, req.ack_round - req.submit_round + 1)
            ),
            "acks": sorted(
                int(r) for r in self._picks[rid][
                    self._pick_valid[rid] & self._acks[rid]
                ]
            ),
            "coordinator": int(self._coord[rid]),
            "retries": req.retries_used,
            "error": req.error,
        }

    def latencies(self, kind: "str | None" = None) -> list:
        """Client-quorum latencies (rounds) of resolved requests, submit
        order — the bench scenario's p50/p99 source."""
        out = []
        for rid in self._order:
            req = self._reqs[rid]
            if kind is not None and req.kind != kind:
                continue
            if req.status == "done" and req.ack_round is not None:
                out.append(max(1, req.ack_round - req.submit_round + 1))
        return out

    def report(self) -> dict:
        """The coordination-layer report (also folded into the health
        surface under ``quorum``): completion/failure counts, latency
        percentiles by kind, retries, repair/push traffic, hint-log
        state."""
        def pct(xs, q):
            if not xs:
                return None
            return float(np.percentile(np.asarray(xs, dtype=np.float64), q))

        gl, pl = self.latencies("get"), self.latencies("put")
        report = {
            "requests": len(self._order),
            "completed": self.completed,
            "failed": self.failed,
            "inflight": self.inflight,
            "retries": self.retries,
            "get_p50_rounds": pct(gl, 50),
            "get_p99_rounds": pct(gl, 99),
            "put_p50_rounds": pct(pl, 50),
            "put_p99_rounds": pct(pl, 99),
            "repaired_rows": self.repaired_rows,
            "pushed_rows": self.pushed_rows,
            "wire_bytes": self.wire_bytes,
            "hints_pending": len(self.hints),
            "hint_replays": self.hints.replays,
            "engine": self.engine,
        }
        get_monitor().observe_quorum(**report)
        return report
