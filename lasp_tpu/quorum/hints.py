"""The durable hint log behind hinted handoff on ``Restore``.

Dynamo/riak hinted handoff: a write whose home replica is unreachable
lands on a fallback node together with a HINT naming the intended home;
when the home returns, the fallback hands the write off before the home
rejoins quorums. The TPU rebuild keeps the protocol's guarantee with a
simpler mechanism suited to the simulation's single host: every
client-ACKED put appends one record — ``(var, preflist, wire row)`` —
to this log; when a crashed replica restores
(``ChaosRuntime._restore`` → the engine's restore hook), every record
whose preflist names it is JOINED into the restored row before the
replica serves another quorum. Join idempotence makes replay harmless
(a row that already absorbed the write is a no-op), and the log is the
mechanism behind the no-acknowledged-write-lost invariant
(``chaos.invariants.check_no_write_lost``): a put acked at W=2 whose
ack replicas BOTH crash and restore from the lattice bottom would
otherwise be lost entirely — the rolling-crash nemesis's signature
failure.

Durability: with a ``path``, every append pickles the record to an
append-only file (flushed per record, the bitcask discipline of the
bridge's host log) and a fresh :class:`HintLog` over the same path
replays the survivors — a process restart keeps its acked writes.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..telemetry import counter, gauge


class HintLog:
    """Append-only log of client-acked quorum puts; see the module doc.

    Records are host trees (numpy leaves) of ONE replica row in the
    runtime's MESH wire format, so replay is a plain leafwise join
    against the live population."""

    def __init__(self, path: "str | None" = None):
        self.path = path
        self.records: list = []  # (var_id, picks int64[N], row-tree, rid)
        #: replica -> record indices naming it (restores scan only their
        #: own slice, not the whole history)
        self._by_replica: dict = {}
        self.replays = 0
        if path is not None and os.path.exists(path):
            self._load(path)

    def __len__(self) -> int:
        return len(self.records)

    def _load(self, path: str) -> None:
        with open(path, "rb") as fp:
            while True:
                try:
                    self._index(pickle.load(fp))
                except EOFError:
                    break

    def _index(self, rec) -> None:
        idx = len(self.records)
        self.records.append(rec)
        for r in rec[1]:
            self._by_replica.setdefault(int(r), []).append(idx)

    def append(self, var_id: str, picks, row, rid: int) -> None:
        """Record one acked put. ``row`` is the put's wire-format row
        (device or host leaves; stored as host copies so the log never
        pins device buffers)."""
        import jax

        host_row = jax.tree_util.tree_map(np.asarray, row)
        rec = (var_id, np.asarray(picks, dtype=np.int64).copy(), host_row,
               int(rid))
        self._index(rec)
        if self.path is not None:
            with open(self.path, "ab") as fp:
                pickle.dump(rec, fp)
                fp.flush()
                os.fsync(fp.fileno())
        gauge(
            "quorum_hints_pending",
            help="hinted-handoff records held for crashed-replica catch-up",
        ).set(len(self.records))

    def pending_for(self, replica: int) -> list:
        """Records whose preflist names ``replica`` — what a restore
        must hand off before the row rejoins quorums. Indexed per
        replica, so a restore scans its own slice, not the whole
        history. Records PERSIST after a replay on purpose: a replica
        that crashes AGAIN and reseeds from bottom needs them again
        (re-joins are idempotent no-ops on caught-up rows); reclaim via
        :meth:`prune` once the population has verifiably converged."""
        return [
            self.records[i]
            for i in self._by_replica.get(int(replica), ())
        ]

    def replay(self, runtime, replica: int) -> int:
        """Hand off every pending hint to a restored replica row: each
        record's row joins into ``states[var][replica]`` (an exact no-op
        where gossip already caught the row up — idempotence). Returns
        the number of rows actually changed. The caller (the quorum
        engine's restore hook) runs this BEFORE the replica serves
        another quorum — the ordering hinted handoff promises."""
        changed = 0
        for var_id, _picks, row, _rid in self.pending_for(replica):
            if var_id not in runtime.var_ids:
                continue
            changed += runtime.join_rows(
                var_id, np.asarray([int(replica)], dtype=np.int64), [row]
            )
        self.replays += 1
        if changed:
            counter(
                "quorum_hint_replays_total",
                help="hinted-handoff rows handed to restored replicas "
                     "(rows actually changed by replay)",
            ).inc(changed)
        return changed

    def prune(self) -> int:
        """Drop every record (call once the population has verifiably
        converged past the log's writes — e.g. after a fault-free
        ``run_to_convergence``). Returns the number dropped. The durable
        file is truncated too."""
        n = len(self.records)
        self.records.clear()
        self._by_replica.clear()
        if self.path is not None and os.path.exists(self.path):
            with open(self.path, "wb"):
                pass
        gauge(
            "quorum_hints_pending",
            help="hinted-handoff records held for crashed-replica catch-up",
        ).set(0)
        return n
