"""The durable hint log behind hinted handoff on ``Restore``.

Dynamo/riak hinted handoff: a write whose home replica is unreachable
lands on a fallback node together with a HINT naming the intended home;
when the home returns, the fallback hands the write off before the home
rejoins quorums. The TPU rebuild keeps the protocol's guarantee with a
simpler mechanism suited to the simulation's single host: every
client-ACKED put appends one record — ``(var, preflist, wire row)`` —
to this log; when a crashed replica restores
(``ChaosRuntime._restore`` → the engine's restore hook), every record
whose preflist names it is JOINED into the restored row before the
replica serves another quorum. Join idempotence makes replay harmless
(a row that already absorbed the write is a no-op), and the log is the
mechanism behind the no-acknowledged-write-lost invariant
(``chaos.invariants.check_no_write_lost``): a put acked at W=2 whose
ack replicas BOTH crash and restore from the lattice bottom would
otherwise be lost entirely — the rolling-crash nemesis's signature
failure.

Durability: with a ``path``, every append pickles the record to an
append-only file (flushed per record, the bitcask discipline of the
bridge's host log) and a fresh :class:`HintLog` over the same path
replays the survivors — a process restart keeps its acked writes.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..telemetry import counter, gauge


class HintLog:
    """Append-only log of client-acked quorum puts; see the module doc.

    Records are host trees (numpy leaves) of ONE replica row in the
    runtime's MESH wire format, so replay is a plain leafwise join
    against the live population."""

    def __init__(self, path: "str | None" = None):
        self.path = path
        self.records: list = []  # (var_id, picks int64[N], row-tree, rid)
        #: replica -> record indices naming it (restores scan only their
        #: own slice, not the whole history)
        self._by_replica: dict = {}
        self.replays = 0
        if path is not None and os.path.exists(path):
            self._load(path)

    def __len__(self) -> int:
        return len(self.records)

    def _load(self, path: str) -> None:
        with open(path, "rb") as fp:
            while True:
                try:
                    self._index(pickle.load(fp))
                except EOFError:
                    break

    def _index(self, rec) -> None:
        idx = len(self.records)
        self.records.append(rec)
        for r in rec[1]:
            self._by_replica.setdefault(int(r), []).append(idx)

    def append(self, var_id: str, picks, row, rid: int) -> None:
        """Record one acked put. ``row`` is the put's wire-format row
        (device or host leaves; stored as host copies so the log never
        pins device buffers)."""
        import jax

        host_row = jax.tree_util.tree_map(np.asarray, row)
        rec = (var_id, np.asarray(picks, dtype=np.int64).copy(), host_row,
               int(rid))
        self._index(rec)
        if self.path is not None:
            with open(self.path, "ab") as fp:
                pickle.dump(rec, fp)
                fp.flush()
                os.fsync(fp.fileno())
        gauge(
            "quorum_hints_pending",
            help="hinted-handoff records held for crashed-replica catch-up",
        ).set(len(self.records))

    def pending_for(self, replica: int) -> list:
        """Records whose preflist names ``replica`` — what a restore
        must hand off before the row rejoins quorums. Indexed per
        replica, so a restore scans its own slice, not the whole
        history. Records PERSIST after a replay on purpose: a replica
        that crashes AGAIN and reseeds from bottom needs them again
        (re-joins are idempotent no-ops on caught-up rows); reclaim via
        :meth:`prune` once the population has verifiably converged."""
        return [
            self.records[i]
            for i in self._by_replica.get(int(replica), ())
        ]

    def replay(self, runtime, replica: int,
               target: "int | None" = None) -> int:
        """Hand off every pending hint naming ``replica``: each
        record's row joins into ``states[var][target]`` — ``target``
        defaults to ``replica`` itself (the restore path); a membership
        finalize passes the departed replica's CLAIM SUCCESSOR instead
        (the lost_src fallback: the replica will never restore, so its
        acked writes land where its ownership went). An exact no-op
        where gossip already caught the target up — idempotence.
        Returns the number of rows actually changed. The restore caller
        (the quorum engine's restore hook) runs this BEFORE the replica
        serves another quorum — the ordering hinted handoff promises."""
        tgt = int(replica if target is None else target)
        changed = 0
        for var_id, _picks, row, _rid in self.pending_for(replica):
            if var_id not in runtime.var_ids:
                continue
            changed += runtime.join_rows(
                var_id, np.asarray([tgt], dtype=np.int64), [row]
            )
        self.replays += 1
        if changed:
            counter(
                "quorum_hint_replays_total",
                help="hinted-handoff rows handed to restored replicas "
                     "(rows actually changed by replay)",
            ).inc(changed)
        return changed

    def prune(self) -> int:
        """Drop every record (call once the population has verifiably
        converged past the log's writes — e.g. after a fault-free
        ``run_to_convergence``). Returns the number dropped. The durable
        file is truncated too."""
        n = len(self.records)
        self.records.clear()
        self._by_replica.clear()
        if self.path is not None and os.path.exists(self.path):
            with open(self.path, "wb"):
                pass
        gauge(
            "quorum_hints_pending",
            help="hinted-handoff records held for crashed-replica catch-up",
        ).set(0)
        return n

    def _reindex(self, keep: list) -> None:
        """Replace the record set (prune rewrite): in-memory index and
        the durable file both rebuild from the survivors."""
        self.records = []
        self._by_replica = {}
        for rec in keep:
            self._index(rec)
        if self.path is not None:
            tmp = self.path + ".prune"
            with open(tmp, "wb") as fp:
                for rec in self.records:
                    pickle.dump(rec, fp)
                fp.flush()
                os.fsync(fp.fileno())
            os.replace(tmp, self.path)
        gauge(
            "quorum_hints_pending",
            help="hinted-handoff records held for crashed-replica catch-up",
        ).set(len(self.records))

    def prune_replayed(self, runtime, replica: int,
                       live=None) -> int:
        """Reclaim the hints a completed replay has RE-ACKED at full
        preflist strength: a record naming ``replica`` drops iff EVERY
        replica its preflist names is live and that replica's row
        already dominates the hinted row (the join would be an exact
        no-op — the write is held at all N homes again, the riak
        delete-after-handoff point). Anything weaker stays: a record
        whose preflist still has a crashed or lagging member remains
        load-bearing for the next bottom-restore (the
        no-acknowledged-write-lost contract). Returns records
        reclaimed. Called from the quorum engine's post-replay restore
        hook; repeat crashes therefore no longer accumulate fully
        re-acked records without bound."""
        import jax

        pending = self._by_replica.get(int(replica))
        if not pending:
            return 0
        if live is None:
            live = np.ones(runtime.n_replicas, dtype=bool)
        live = np.asarray(live, dtype=bool)
        drop: set = set()
        for i in pending:
            var_id, picks, row, _rid = self.records[i]
            if var_id not in runtime.var_ids:
                continue
            if not live[np.asarray(picks, dtype=np.int64)].all():
                continue
            pop = runtime._population(var_id)
            codec, spec = runtime._mesh_meta(var_id)
            dominated = True
            for p in picks:
                cur = jax.tree_util.tree_map(
                    lambda x: x[int(p)], pop
                )
                merged = codec.merge(spec, cur, row)
                if not bool(codec.equal(spec, merged, cur)):
                    dominated = False
                    break
            if dominated:
                drop.add(i)
        if not drop:
            return 0
        self._reindex(
            [rec for i, rec in enumerate(self.records) if i not in drop]
        )
        counter(
            "quorum_hints_pruned_total",
            help="hinted-handoff records reclaimed after full-preflist "
                 "re-ack (post-replay restore path)",
        ).inc(len(drop))
        return len(drop)
