"""Vectorized quorum coordination — the reference's request FSMs as
batched tensor steps (Lasp L3, ROADMAP open item 4).

The reference coordinates every client request through one of 18
``gen_fsm`` modules: prepare → execute → waiting(R) → waiting_n(N) →
finalize/repair with N=3, R=W=2 (``src/lasp_update_fsm.erl:174-216``,
``src/lasp_read_fsm.erl:125-146``) plus ring-coverage merges
(``src/lasp_execute_coverage_fsm.erl:50-97``). One Erlang process per
in-flight request is exactly the shape that does NOT map to an
accelerator — so this package re-expresses the layer as data-parallel
tensor steps ("Mapping the Join Calculus to Heterogeneous Hardware",
PAPERS.md): a request batch is a struct-of-arrays FSM advanced by ONE
jitted transition kernel per round, drawing reachability from the same
per-round edge masks the chaos schedule compiles, with the join work
(get values, read-repair, put replication) dispatched as masked partial
joins per variable (Tascade's barrier-free reduction discipline:
coordination never stalls gossip).

Modules:

- :mod:`.fsm` — state vocabulary, deterministic preflists, per-round
  component labeling over the chaos mask, and the two transition
  implementations (the batched jit kernel and the per-request scalar
  reference they are asserted bit-identical against);
- :mod:`.engine` — :class:`QuorumRuntime`: submit/step/drain over a
  ``ChaosRuntime`` (or bare ``ReplicatedRuntime``), read-repair as
  masked partial joins, per-request timeout/retry with coordinator
  re-pick, and the latency/staleness report the bench scenario lifts;
- :mod:`.hints` — the durable hint log behind hinted handoff on
  ``Restore`` (the no-acknowledged-write-lost invariant's mechanism);
- :mod:`.coverage` — ring-coverage queries: partition-sweep map-merge
  over all shards, one grouped dispatch per plan group, feeding
  ``programs/riak_index.py``.

docs/RESILIENCE.md "Quorum coordination" documents semantics vs the
reference; ``tools/quorum_smoke.py`` (Makefile ``verify``) guards the
batched-vs-sequential bit-identity contract.
"""

from ..membership.errors import StaleEpochError
from .engine import PartialQuorumError, QuorumRuntime
from .fsm import DONE, FAILED, PREPARE, REPAIR, STATE_NAMES, WAITING_N, WAITING_R
from .hints import HintLog
from .coverage import coverage_sweep, ring_coverage_execute

__all__ = [
    "QuorumRuntime",
    "PartialQuorumError",
    "StaleEpochError",
    "HintLog",
    "coverage_sweep",
    "ring_coverage_execute",
    "PREPARE",
    "WAITING_R",
    "WAITING_N",
    "REPAIR",
    "DONE",
    "FAILED",
    "STATE_NAMES",
]
