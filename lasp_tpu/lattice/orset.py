"""OR-Set (observe-remove set) as dense (exists, removed) token tensors.

Reference semantics (``src/lasp_orset.erl``): state is an orddict
``elem -> orddict(token -> removed?)``; ``add`` creates a fresh unique token
with flag ``false`` (:101-105, :222-230), ``remove`` flips every currently
held token of the element to ``true`` (tombstones, :232-241), ``merge`` is a
per-(elem, token) OR of the removed flags plus union of tokens (:128-134),
and an element is in the ``value`` iff it holds at least one live token
(:67-73). Order theory (``src/lasp_lattice.erl:153-161, 235-253``): inflation
= every (elem, token) of the previous state is still present (flags
irrelevant — ``ids_inflated`` :277-285); strict inflation additionally needs
a flag flip on a shared element, a new token on a shared element, or a new
element.

Dense encoding. The reference mints 20 random bytes per add via crypto NIFs
(``src/lasp_orset.erl:261-262``); unbounded random identity cannot live in a
fixed-shape tensor. Instead token identity is *counter-based and
deterministic*: writer actor ``a``'s ``k``-th add of a given element owns
token slot ``a * tokens_per_actor + k``. Collision-freedom holds by
construction (single-writer actor counters), so merge alignment is exact and
no randomness (and no host round-trip) is needed — this replaces the
crypto/druuid native dependency (SURVEY.md §2.4).

State is ``exists: bool[E, T]``, ``removed: bool[E, T]`` with
``T = n_actors * tokens_per_actor``. Merge = two elementwise ORs — the hot
kernel of the whole framework (reference hot path
``src/lasp_core.erl:300-301``), vmapped over replicas and usable directly as
an ``all_reduce`` operator over mesh axes. (A bit-packed ``uint32`` variant
for HBM-bound scale is planned for ``lasp_tpu.ops``.)
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .base import CrdtType


@dataclasses.dataclass(frozen=True)
class ORSetSpec:
    n_elems: int
    n_actors: int
    tokens_per_actor: int = 4
    #: explicit token-space size for *derived* variables (combinator outputs),
    #: whose tokens are projections/products of their inputs' token spaces
    #: rather than actor-minted slots; None = n_actors * tokens_per_actor.
    token_space: int | None = None

    @property
    def n_tokens(self) -> int:
        if self.token_space is not None:
            return self.token_space
        return self.n_actors * self.tokens_per_actor


class ORSetState(NamedTuple):
    exists: jax.Array  # bool[E, T] — token minted
    removed: jax.Array  # bool[E, T] — tombstone flag (valid where exists)


class ORSet(CrdtType):
    name = "lasp_orset"
    leafwise_join = "or"

    @staticmethod
    def new(spec: ORSetSpec) -> ORSetState:
        shape = (spec.n_elems, spec.n_tokens)
        return ORSetState(
            exists=jnp.zeros(shape, dtype=bool),
            removed=jnp.zeros(shape, dtype=bool),
        )

    # -- updates ------------------------------------------------------------
    @staticmethod
    def add_exhausted(
        spec: ORSetSpec, state: ORSetState, elem_idx, actor_idx
    ) -> jax.Array:
        """Scalar bool: the actor's token pool for the element is full, so an
        ``add`` here would be dropped. The host op layers (store updates,
        ``ReplicatedRuntime.update_batch``) check this and raise a loud
        ``CapacityError`` — the reference never drops adds
        (``src/lasp_orset.erl:222-230`` always mints), so a silent drop would
        be a semantic divergence; pure-device batch kernels that cannot raise
        surface saturation via ``stats()['full_pools']`` instead."""
        k = spec.tokens_per_actor
        pool = jax.lax.dynamic_slice(
            state.exists[elem_idx], (actor_idx * k,), (k,)
        )
        return jnp.all(pool)

    @staticmethod
    def add(spec: ORSetSpec, state: ORSetState, elem_idx, actor_idx) -> ORSetState:
        """``update({add, Elem}, Actor)`` — mint the actor's next token for
        the element (``src/lasp_orset.erl:103-105``). Jittable with traced
        indices. The first *free* slot in the actor's pool is used (robust to
        interleaved ``add_by_token`` writes); if the pool is exhausted the
        add is a no-op at this level (fixed shapes cannot grow) — callers on
        the host path gate on :meth:`add_exhausted` and raise
        ``CapacityError`` so exhaustion is never silent."""
        k = spec.tokens_per_actor
        base = actor_idx * k
        row = state.exists[elem_idx]
        pool = jax.lax.dynamic_slice(row, (base,), (k,))
        free = jnp.argmax(~pool)  # first free slot, 0 if pool is full
        in_range = ~pool[free]
        slot = base + free
        exists = state.exists.at[elem_idx, slot].set(
            state.exists[elem_idx, slot] | in_range
        )
        # a freshly minted token is live even if that lane once carried a
        # tombstone (cannot happen via our own ops, but keep add total)
        removed = state.removed.at[elem_idx, slot].set(
            state.removed[elem_idx, slot] & ~in_range
        )
        return ORSetState(exists=exists, removed=removed)

    @staticmethod
    def add_by_token(
        spec: ORSetSpec, state: ORSetState, elem_idx, token_idx
    ) -> ORSetState:
        """``update({add_by_token, Token, Elem})`` (``src/lasp_orset.erl:101-102``):
        insert a caller-supplied token with a fresh (live) flag."""
        return ORSetState(
            exists=state.exists.at[elem_idx, token_idx].set(True),
            removed=state.removed.at[elem_idx, token_idx].set(False),
        )

    @staticmethod
    def remove(spec: ORSetSpec, state: ORSetState, elem_idx) -> ORSetState:
        """``update({remove, Elem})`` — tombstone every *observed* token of the
        element (``src/lasp_orset.erl:232-241``). The precondition check
        (element present) is the caller's job (the store layer does it), matching
        the reference's ``{error, {precondition, {not_present, E}}}``."""
        row_removed = state.removed[elem_idx] | state.exists[elem_idx]
        return ORSetState(
            exists=state.exists,
            removed=state.removed.at[elem_idx].set(row_removed),
        )

    @staticmethod
    def apply_masks(
        spec: ORSetSpec, state: ORSetState, add_tokens: jax.Array, remove_elems: jax.Array
    ) -> ORSetState:
        """Batched device-side update kernel: OR-in freshly minted tokens
        (``add_tokens: bool[E, T]``) and tombstone all observed tokens of the
        elements flagged in ``remove_elems: bool[E]``. This is the form the
        large-scale simulations drive (one fused call per round per replica
        population)."""
        exists = state.exists | add_tokens
        removed = state.removed | (exists & remove_elems[..., None])
        return ORSetState(exists=exists, removed=removed)

    # -- lattice ------------------------------------------------------------
    @staticmethod
    def merge(spec: ORSetSpec, a: ORSetState, b: ORSetState) -> ORSetState:
        # union of tokens; OR of tombstone flags (src/lasp_orset.erl:128-134)
        return ORSetState(exists=a.exists | b.exists, removed=a.removed | b.removed)

    @staticmethod
    def value(spec: ORSetSpec, state: ORSetState) -> jax.Array:
        """bool[E]: element holds >=1 live token (``src/lasp_orset.erl:67-73``)."""
        return jnp.any(state.exists & ~state.removed, axis=-1)

    @staticmethod
    def removed_value(spec: ORSetSpec, state: ORSetState) -> jax.Array:
        """bool[E]: elements with >=1 tombstoned token
        (``value(removed, _)``, ``src/lasp_orset.erl:90-95``)."""
        return jnp.any(state.exists & state.removed, axis=-1)

    @staticmethod
    def member_mask(spec: ORSetSpec, state: ORSetState) -> jax.Array:
        """bool[E]: element appears in the state at all (live or tombstoned) —
        the orddict key set, which combinators iterate
        (``src/lasp_core.erl:647-655`` folds raw state, not value)."""
        return jnp.any(state.exists, axis=-1)

    @staticmethod
    def equal(spec: ORSetSpec, a: ORSetState, b: ORSetState) -> jax.Array:
        return jnp.all(a.exists == b.exists) & jnp.all(
            (a.removed & a.exists) == (b.removed & b.exists)
        )

    @staticmethod
    def is_inflation(spec: ORSetSpec, prev: ORSetState, cur: ORSetState) -> jax.Array:
        # token ids preserved; flags not consulted (ids_inflated,
        # src/lasp_lattice.erl:277-285) — but tombstones only ever grow, so
        # flag regressions cannot occur under merge/update anyway.
        return jnp.all(~prev.exists | cur.exists)

    @staticmethod
    def is_strict_inflation(
        spec: ORSetSpec, prev: ORSetState, cur: ORSetState
    ) -> jax.Array:
        """``src/lasp_lattice.erl:235-253``: inflation AND (a shared element's
        token dict changed — new token or flag flip — OR the element count
        grew)."""
        inflation = jnp.all(~prev.exists | cur.exists)
        elem_prev = jnp.any(prev.exists, axis=-1)
        elem_cur = jnp.any(cur.exists, axis=-1)
        shared = elem_prev & elem_cur
        row_changed = jnp.any(
            (prev.exists != cur.exists)
            | ((prev.removed & prev.exists) != (cur.removed & cur.exists)),
            axis=-1,
        )
        deleted_or_grown = jnp.any(shared & row_changed)
        new_elements = jnp.sum(elem_cur) > jnp.sum(elem_prev)
        return inflation & (deleted_or_grown | new_elements)

    # -- introspection ------------------------------------------------------
    @staticmethod
    def precondition_context(spec: ORSetSpec, state: ORSetState) -> ORSetState:
        """Fragment of observed *live* adds (``src/lasp_orset.erl:147-154``)."""
        live = state.exists & ~state.removed
        return ORSetState(exists=live, removed=jnp.zeros_like(live))

    @staticmethod
    def stats(spec: ORSetSpec, state: ORSetState) -> dict:
        """element/adds/removes/waste_pct per ``src/lasp_orset.erl:156-192``,
        plus ``full_pools``: the number of (element, actor) token pools with
        no free slot — the observable form of pool exhaustion for device-side
        batch updates that cannot raise (VERDICT: dropped adds must never be
        invisible). Only meaningful for actor-minted layouts (derived
        combinator outputs use projected token spaces and report 0)."""
        exists = state.exists
        live = int(jnp.sum(exists & ~state.removed))
        dead = int(jnp.sum(exists & state.removed))
        total = live + dead
        if spec.token_space is None:
            pools = exists.reshape(
                exists.shape[:-1] + (spec.n_actors, spec.tokens_per_actor)
            )
            full_pools = int(jnp.sum(jnp.all(pools, axis=-1)))
        else:
            full_pools = 0
        return {
            "element_count": int(jnp.sum(jnp.any(exists, axis=-1))),
            "adds_count": live,
            "removes_count": dead,
            "waste_pct": 0 if live == 0 else round(dead / total * 100),
            "full_pools": full_pools,
        }
