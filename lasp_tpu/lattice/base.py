"""Lattice type contract: the TPU-native analogue of the ``riak_dt`` behaviour.

The reference framework (Lasp) represents CRDT state as Erlang terms and
requires every type to export ``new/0, update/3, merge/2, equal/2, value/1``
(reference: ``src/lasp_orset.erl:32-36``) plus the order-theoretic predicates
in ``src/lasp_lattice.erl`` (``threshold_met/3``, ``is_lattice_inflation/3``,
``is_lattice_strict_inflation/3``).

Here every CRDT type is a *dense tensor codec*:

- a static, hashable ``Spec`` (capacities: element universe size, number of
  writer actors, token budget) that fixes array shapes so every operation is
  jit-compilable;
- a ``State`` pytree of ``jax.Array`` leaves carrying the lattice value;
- pure functions ``new / update ops / merge / value / equal / is_inflation /
  is_strict_inflation / threshold_met`` that are jittable and ``vmap``-able
  over a leading replica axis.

Because join is associative, commutative, and idempotent, ``merge`` is safe
to use as a collective reduction operator (``all_reduce``) and under any
gossip schedule — the property that makes the bulk-synchronous TPU execution
equivalent to Lasp's asynchronous per-process execution (the same argument
that makes read-repair sound, reference ``src/lasp_update_fsm.erl:189-216``).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, ClassVar, NamedTuple

import jax
import jax.numpy as jnp


class Threshold(NamedTuple):
    """A monotone read threshold: a lattice state plus a strictness flag.

    Mirrors the reference's ``threshold() :: value() | {strict, value()}``
    (``include/lasp.hrl``); ``{strict, V}`` demands a *strict* inflation past
    ``V`` (``src/lasp_lattice.erl:51-90``).
    """

    state: Any
    strict: bool = False


class CrdtType(abc.ABC):
    """Namespace-style contract every lattice type implements.

    Subclasses are stateless; all methods are pure functions over ``State``
    pytrees and are usable under ``jax.jit`` / ``jax.vmap`` unless marked
    host-only. ``name`` matches the reference module name for parity tracing.
    """

    #: reference module this type is equivalent to (e.g. "lasp_orset")
    name: ClassVar[str] = ""

    #: declares that ``merge`` is the SAME elementwise join on every state
    #: leaf — "or" (bitwise/boolean) or "max" — with no cross-leaf
    #: coupling. Hot paths (``mesh.gossip.gossip_round``) then process
    #: each leaf in one fused expression instead of materializing a
    #: per-neighbor-column intermediate across the whole pytree (measured
    #: 1.5x on the CPU host at the bench headline shape). None = merge
    #: has structure (vclock domination, epoch gates): use the generic
    #: per-column path.
    leafwise_join: ClassVar["str | None"] = None

    # -- construction -------------------------------------------------------
    @staticmethod
    @abc.abstractmethod
    def new(spec) -> Any:
        """Bottom element of the lattice for this spec (``Type:new/0``)."""

    # -- lattice operations (jittable) -------------------------------------
    @staticmethod
    @abc.abstractmethod
    def merge(spec, a, b) -> Any:
        """Join (least upper bound) of two states (``Type:merge/2``)."""

    @staticmethod
    @abc.abstractmethod
    def value(spec, state) -> Any:
        """Observable value of the state (``Type:value/1``) as arrays."""

    @staticmethod
    @abc.abstractmethod
    def equal(spec, a, b) -> jax.Array:
        """Scalar bool array: state equality (``Type:equal/2``)."""

    @staticmethod
    @abc.abstractmethod
    def is_inflation(spec, prev, cur) -> jax.Array:
        """``cur`` >= ``prev`` in the lattice order
        (``src/lasp_lattice.erl:126-179``)."""

    @staticmethod
    @abc.abstractmethod
    def is_strict_inflation(spec, prev, cur) -> jax.Array:
        """``cur`` > ``prev`` strictly (``src/lasp_lattice.erl:204-275``)."""

    @classmethod
    def threshold_met(cls, spec, state, threshold: Threshold) -> jax.Array:
        """Default threshold semantics: (strict) inflation beyond the
        threshold state — the rule shared by gset/orset/orswot/map
        (``src/lasp_lattice.erl:62-85``). Counter- and ivar-like types
        override."""
        if threshold.strict:
            return cls.is_strict_inflation(spec, threshold.state, state)
        return cls.is_inflation(spec, threshold.state, state)

    # -- host-side helpers --------------------------------------------------
    @staticmethod
    def stats(spec, state) -> dict:
        """Introspection counters (``Type:stats/1``); optional."""
        return {}


def tree_all_equal(a, b) -> jax.Array:
    """Scalar bool: every leaf of two same-structure pytrees is elementwise
    equal. Used as the default ``equal`` for tensor-encoded states."""
    struct_a = jax.tree_util.tree_structure(a)
    struct_b = jax.tree_util.tree_structure(b)
    if struct_a != struct_b:
        raise ValueError(
            f"tree_all_equal: mismatched pytree structures {struct_a} vs {struct_b}"
        )
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    acc = jnp.asarray(True)
    for la, lb in zip(leaves_a, leaves_b):
        acc = jnp.logical_and(acc, jnp.all(la == lb))
    return acc


def replicate(state, n_replicas: int):
    """Broadcast a single-replica state to a leading replica axis.

    The replica axis is the TPU analogue of Lasp's N-way preflist placement
    (``src/lasp.erl:345-366``): one slice per simulated replica, merged by
    vmapped joins instead of quorum FSMs.
    """
    return jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf, (n_replicas,) + leaf.shape), state
    )


@dataclasses.dataclass(frozen=True)
class TypeRegistry:
    """Maps reference type names to codec classes (parity with the accepted
    ``type()`` union in ``include/lasp.hrl:76``)."""

    types: tuple = ()

    def get(self, name: str) -> type:
        for t in self.types:
            if t.name == name or t.__name__ == name:
                return t
        raise KeyError(f"unknown lattice type: {name}")
