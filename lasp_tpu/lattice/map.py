"""CRDT Map: composed field lattices under observe-remove key presence.

Reference semantics (external dep ``riak_dt_map``, used by the KVS-replica
workload ``riak_test/lasp_kvs_replica_test.erl:57-135`` and ordered by the
framework at ``src/lasp_lattice.erl:166-167, 264-271``): state is
``{VClock, Entries, Deferred}`` where entries map ``{Name, Type}`` field
keys to embedded CRDTs plus presence dots; ``{update, [{update, Key, Op} |
{remove, Key}]}`` applies batched field ops; merge is OR-SWOT presence
logic over keys plus per-field embedded merge; inflation = clock descends,
strict inflation = dominating clock or equal clocks with removed fields.

Dense encoding: a ``MapSpec`` holds the ordered tuple of (key, embedded
codec, embedded spec) — so a Map state is ``clock: int32[A]``, ``dots:
int32[F, A]`` (presence, exactly the ORSWOT dot matrix over field slots)
and a tuple of embedded states. The schema is *dynamic the way the
reference's is* (``riak_dt_map`` admits ``{Name, Type}`` keys on first
update, ``riak_test/lasp_kvs_replica_test.erl:57-135``): the store layer
admits unknown keys by growing the field axis — a new spec with the field
appended plus :meth:`CrdtMap.grow` to append bottom slots to live states
(the same grow-then-re-layout move interners use for element universes).
Declaring fields up front remains a pre-sizing fast path, not a fence.
Maps NEST: a ``{Name, riak_dt_map}`` field embeds a submap (to any
depth) with the same dynamic admission, the parent's re-add mode, and —
in reset mode — riak_dt's RECURSIVE reset-remove (removing a submap
field erases exactly what was observed at every level of the subtree;
see :func:`_reset_field`).

Remove/re-add semantics — two modes:

- default (``reset_on_readd=False``): contents are join-monotone across
  remove/re-add (presence controls visibility only) — the trade that
  keeps merge a pure elementwise lattice join over fixed shapes.
- ``reset_on_readd=True``: ``riak_dt_map``'s observable semantics
  (``riak_test/lasp_kvs_replica_test.erl:61-129``), including riak_dt's
  *reset-remove* under concurrency (round 5 — closing the r4 epoch-gate
  divergence): a remove erases exactly what the remover OBSERVED; an
  update concurrent with the remove keeps its own contribution. The
  reset is expressed per embedded type, always as a lattice join:

  * OR-Set-family fields: remove tombstones the observed tokens
    (``removed |= exists``) — concurrent adds mint unseen tokens and
    survive; a re-add yields fresh contents. Exactly riak_dt. COST: the
    tombstones pin their token slots, so remove/re-add cycling a field
    exhausts the fixed per-actor pool after ``tokens_per_actor`` cycles
    with a loud ``CapacityError`` — the same bounded-shape trade as
    top-level OR-Set removes. Reclamation:
    ``Store.compact_map_field`` (single store) /
    ``ReplicatedRuntime.compact_map_field`` (whole population, gated on
    divergence 0) free fully-tombstoned element rows at quiescence, so
    sized pools sustain unbounded churn.
  * OR-SWOT fields: remove drops the observed birth dots (clock kept) —
    the standard orswot remove-all; concurrent adds' fresh dots escape
    the remover's clock and survive. Exactly riak_dt.
  * G-Counter fields: the state cannot express removal, so the map
    carries a per-field *tombstone baseline* (``tombs``: the observed
    counts at remove, lane-joined by max); the observable value
    subtracts the baseline (``CrdtMap.effective_field``). Concurrent
    increments exceed the baseline on their own lane and survive —
    riak_dt_emcntr's observable.
  * G-Set / IVar fields (NOT riak_dt embedded types — this framework's
    extensions): neither state can distinguish a re-add from a merged
    old copy (no tokens, no dots), so a baseline would drop SEQUENTIAL
    re-adds forever. They reset to bottom behind the per-field *epoch*
    gate instead (``epochs: int32[F]``; merge joins their contents only
    between equal eras) — sequential remove/re-add yields fresh
    contents; an update concurrent with a remove keeps presence but
    loses its era's contents (the r4-documented trade, now scoped to
    these two types only). Epochs are bumped on EVERY remove
    regardless of type: they witness resets for the strict-inflation
    rule.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .base import CrdtType
from .dots import clock_inflation, merge_dots, mint_dot, strict_clock_inflation


@dataclasses.dataclass(frozen=True)
class MapSpec:
    #: ordered schema: ((key, codec_cls, embedded_spec), ...) — grows via
    #: ``with_fields`` when the store admits a key on first update
    fields: tuple
    n_actors: int
    #: riak_dt re-add semantics: remove resets embedded contents via a
    #: per-field epoch (see module docstring)
    reset_on_readd: bool = False

    @property
    def n_fields(self) -> int:
        return len(self.fields)

    def field_index(self, key) -> int:
        # lazy key->slot dict (dynamic admission makes F unbounded, and
        # the batch paths look up per sub-op): cached in __dict__ via
        # object.__setattr__ — derived data, not dataclass state, and
        # with_fields/replace build fresh instances so it never goes stale
        idx = self.__dict__.get("_key_index")
        if idx is None:
            idx = {k: i for i, (k, _c, _s) in enumerate(self.fields)}
            object.__setattr__(self, "_key_index", idx)
        try:
            return idx[key]
        except (KeyError, TypeError):
            raise KeyError(
                f"riak_dt_map: unknown field {key!r} (dynamic admission "
                "requires (name, type_name) keys, riak_dt_map's {Name, Type})"
            ) from None

    def with_fields(self, new_fields) -> "MapSpec":
        """A grown spec with ``new_fields`` ((key, codec, espec) triples)
        appended in order — existing field indices are preserved, so live
        states migrate by appending bottom slots (:meth:`CrdtMap.grow`)."""
        return dataclasses.replace(self, fields=self.fields + tuple(new_fields))

    def replace_field_spec(self, field_idx: int, espec) -> "MapSpec":
        """A spec with one field's embedded spec replaced — how a NESTED
        map field's growth propagates to its parent (the parent's triple
        must track the submap's evolving schema)."""
        fields = list(self.fields)
        k, codec, _old = fields[field_idx]
        fields[field_idx] = (k, codec, espec)
        return dataclasses.replace(self, fields=tuple(fields))


def _resets(spec: MapSpec) -> bool:
    # works on pre-round-4 unpickled MapSpecs too: the field is absent
    # from their __dict__, but the dataclass default is a class attribute,
    # so plain access falls back to False
    return bool(spec.reset_on_readd)


def map_subs(op: tuple) -> list:
    """Flatten a map client op to its sub-ops: the batched shape
    ``("update", [SubOps])`` yields its list, a single field op yields
    itself. The ONE definition of the batch grammar's outer layer — the
    vectorized batch's shape validation and the reset-remove routing
    check (``mesh/runtime.py``) must never parse it differently."""
    return op[1] if op[0] == "update" and len(op) == 2 else [op]


class MapState(NamedTuple):
    clock: jax.Array  # int32[A]
    dots: jax.Array  # int32[F, A] — field-presence dots (ORSWOT logic)
    fields: tuple  # embedded states, schema order
    #: int32[F] reset eras (reset_on_readd mode), else None
    epochs: "jax.Array | None" = None
    #: reset-remove tombstone baselines (reset mode), schema order: per
    #: field an observed-counts / observed-mask plane joined by max/OR,
    #: or None for types that express reset in-state (see module doc)
    tombs: "tuple | None" = None


#: embedded types whose reset rides the per-field epoch gate (see the
#: module doc: no tokens/dots to distinguish re-adds from merged copies)
_EPOCH_GATED = ("lasp_ivar", "lasp_gset")


def _tomb_bottom(codec, espec):
    """The tombstone-baseline bottom for one embedded field, or None for
    types that need none (reset rides in-state or behind the epoch
    gate)."""
    if codec.name == "riak_dt_gcounter":
        return jnp.zeros((espec.n_actors,), dtype=espec.dtype)
    return None


def _reset_field(codec, espec, fs, tomb):
    """The ONE per-type reset-remove rule (module docstring), shared by a
    single field's :meth:`CrdtMap.remove` and the whole-map
    :meth:`CrdtMap.reset_observed`: returns ``(new_field_state,
    new_tomb)``."""
    if codec.name == "riak_dt_map":
        # recursive reset-remove: erase what was observed at EVERY level
        # of the subtree (riak_dt's remove recurses into embedded maps)
        return CrdtMap.reset_observed(espec, fs), tomb
    if codec.name in ("lasp_orset", "lasp_orset_gbtree"):
        return fs._replace(removed=fs.removed | fs.exists), tomb
    if codec.name == "riak_dt_orswot":
        return fs._replace(dots=jnp.zeros_like(fs.dots)), tomb
    if codec.name == "riak_dt_gcounter":
        return fs, jnp.maximum(tomb, fs.counts)
    # epoch-gated types (gset/ivar): bottom-reset
    return codec.new(espec), tomb


class CrdtMap(CrdtType):
    name = "riak_dt_map"

    @staticmethod
    def new(spec: MapSpec) -> MapState:
        return MapState(
            clock=jnp.zeros((spec.n_actors,), dtype=jnp.int32),
            dots=jnp.zeros((spec.n_fields, spec.n_actors), dtype=jnp.int32),
            fields=tuple(codec.new(espec) for _k, codec, espec in spec.fields),
            epochs=(
                jnp.zeros((spec.n_fields,), dtype=jnp.int32)
                if _resets(spec)
                else None
            ),
            tombs=(
                tuple(
                    _tomb_bottom(codec, espec)
                    for _k, codec, espec in spec.fields
                )
                if _resets(spec)
                else None
            ),
        )

    @staticmethod
    def grow(spec: MapSpec, state: MapState) -> MapState:
        """Migrate a state laid out for a field-prefix of ``spec`` by
        appending bottom slots for the new fields (admitted keys carry no
        presence dots and bottom contents, so growth is observably a
        no-op until the first update lands). Works on any leading batch
        axes — the mesh layer grows whole replica populations in place."""
        f_old = state.dots.shape[-2]
        f_new = spec.n_fields
        batch = state.dots.shape[:-2]
        fields = list(state.fields)
        changed = f_new != f_old
        for f in range(f_old):
            # existing NESTED map fields may themselves have grown (their
            # espec gained subfields): recurse so one top-level grow
            # migrates the whole tree
            _k, codec, espec = spec.fields[f]
            if codec.name == "riak_dt_map":
                grown_sub = CrdtMap.grow(espec, fields[f])
                changed = changed or grown_sub is not fields[f]
                fields[f] = grown_sub
        if not changed:
            return state
        dots = state.dots
        if f_new != f_old:
            dots = jnp.concatenate(
                [
                    dots,
                    jnp.zeros(
                        batch + (f_new - f_old, spec.n_actors), dots.dtype
                    ),
                ],
                axis=-2,
            )
        for _k, codec, espec in spec.fields[f_old:]:
            bottom = codec.new(espec)
            if batch:
                bottom = jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(x, batch + x.shape), bottom
                )
            fields.append(bottom)
        epochs = state.epochs
        if epochs is not None and f_new != f_old:
            epochs = jnp.concatenate(
                [epochs, jnp.zeros(batch + (f_new - f_old,), epochs.dtype)],
                axis=-1,
            )
        tombs = state.tombs
        if tombs is not None:
            grown = list(tombs)
            for _k, codec, espec in spec.fields[f_old:]:
                bt = _tomb_bottom(codec, espec)
                if batch and bt is not None:
                    bt = jnp.broadcast_to(bt, batch + bt.shape)
                grown.append(bt)
            tombs = tuple(grown)
        return state._replace(
            dots=dots, fields=tuple(fields), epochs=epochs, tombs=tombs
        )

    # -- updates ------------------------------------------------------------
    @staticmethod
    def touch(spec: MapSpec, state: MapState, field_idx: int, actor_idx) -> MapState:
        """Mark a field present with a fresh dot (the presence half of
        ``{update, Key, Op}``); the embedded op is applied by the caller."""
        clock, dots = mint_dot(state.clock, state.dots, field_idx, actor_idx)
        return state._replace(clock=clock, dots=dots)

    @staticmethod
    def set_field(spec: MapSpec, state: MapState, field_idx: int, fstate) -> MapState:
        fields = list(state.fields)
        fields[field_idx] = fstate
        return state._replace(fields=tuple(fields))

    @staticmethod
    def remove(spec: MapSpec, state: MapState, field_idx: int) -> MapState:
        """``{remove, Key}``: drop the presence dots; the clock witnesses
        them so merges cannot resurrect the removal. In reset mode the
        embedded contents are reset-removed — erasing exactly what this
        replica observed, per the type-specific rules in the module doc —
        and the field's epoch advances (a reset witness for strict
        inflation; the merge gate for ivar fields only)."""
        out = state._replace(dots=state.dots.at[field_idx].set(0))
        if not _resets(spec):
            return out
        f = field_idx
        _k, codec, espec = spec.fields[f]
        fields = list(out.fields)
        tombs = list(out.tombs)
        fs = fields[f]
        fields[f], tombs[f] = _reset_field(codec, espec, fs, tombs[f])
        return out._replace(
            fields=tuple(fields),
            tombs=tuple(tombs),
            epochs=out.epochs.at[f].add(1),
        )

    @staticmethod
    def reset_observed(spec: MapSpec, state: MapState) -> MapState:
        """The reset-remove of an ENTIRE map state: drop every observed
        presence dot, bump every field's epoch, and reset each field's
        contents per its type (the same per-type rules as
        :meth:`remove`, applied to all fields at once, recursively for
        nested maps). Used when a PARENT map's field holding this map is
        removed in reset mode — exactly what was observed here dies;
        concurrent unseen updates survive the later merge."""
        fields = list(state.fields)
        tombs = (
            list(state.tombs)
            if state.tombs is not None
            else [None] * len(fields)
        )
        for f, (_k, codec, espec) in enumerate(spec.fields):
            fields[f], tombs[f] = _reset_field(
                codec, espec, fields[f], tombs[f]
            )
        out = state._replace(
            dots=jnp.zeros_like(state.dots),
            fields=tuple(fields),
        )
        if state.epochs is not None:
            out = out._replace(
                epochs=state.epochs + 1, tombs=tuple(tombs)
            )
        return out

    @staticmethod
    def effective_field(spec: MapSpec, state: MapState, field_idx: int):
        """The embedded state with reset-remove tombstone baselines
        applied — what ``value`` decoding must read. The ONE definition
        of the subtraction; plain-mode maps (and tomb-less field types)
        return the raw embedded state."""
        fs = state.fields[field_idx]
        if state.tombs is None or state.tombs[field_idx] is None:
            return fs
        tomb = state.tombs[field_idx]
        # riak_dt_gcounter (the one tomb-carrying type): a row that has
        # not yet absorbed the counts its tomb floor witnesses must clip
        # at zero, never go negative
        return fs._replace(counts=fs.counts - jnp.minimum(fs.counts, tomb))

    # -- lattice ------------------------------------------------------------
    @staticmethod
    def merge(spec: MapSpec, a: MapState, b: MapState) -> MapState:
        clock, dots = merge_dots(a.clock, a.dots, b.clock, b.dots)
        if not _resets(spec):
            fields = tuple(
                codec.merge(espec, fa, fb)
                for (_k, codec, espec), fa, fb in zip(
                    spec.fields, a.fields, b.fields
                )
            )
            return MapState(clock=clock, dots=dots, fields=fields)
        # reset mode: contents join plainly (resets ride in-state or in
        # the tombs baselines, which join by max); only the epoch-gated
        # types (gset/ivar) join between equal eras, the side that has
        # observed fewer resets contributing bottom
        epochs = jnp.maximum(a.epochs, b.epochs)
        fields = []
        tombs = []
        for f, ((_k, codec, espec), fa, fb, ta, tb) in enumerate(
            zip(spec.fields, a.fields, b.fields, a.tombs, b.tombs)
        ):
            if codec.name in _EPOCH_GATED:
                bottom = codec.new(espec)
                fa = jax.tree_util.tree_map(
                    lambda x, bot: jnp.where(a.epochs[f] == epochs[f], x, bot),
                    fa, bottom,
                )
                fb = jax.tree_util.tree_map(
                    lambda x, bot: jnp.where(b.epochs[f] == epochs[f], x, bot),
                    fb, bottom,
                )
            fields.append(codec.merge(espec, fa, fb))
            tombs.append(
                None if ta is None else jnp.maximum(ta, tb)
            )
        return MapState(
            clock=clock, dots=dots, fields=tuple(fields), epochs=epochs,
            tombs=tuple(tombs),
        )

    @staticmethod
    def value(spec: MapSpec, state: MapState) -> jax.Array:
        """bool[F]: field presence mask (embedded values decode host-side)."""
        return jnp.any(state.dots > 0, axis=-1)

    @staticmethod
    def equal(spec: MapSpec, a: MapState, b: MapState) -> jax.Array:
        acc = jnp.all(a.clock == b.clock) & jnp.all(a.dots == b.dots)
        if _resets(spec):
            acc = acc & jnp.all(a.epochs == b.epochs)
            for ta, tb in zip(a.tombs, b.tombs):
                if ta is not None:
                    acc = acc & jnp.all(ta == tb)
        for (_k, codec, espec), fa, fb in zip(spec.fields, a.fields, b.fields):
            acc = acc & codec.equal(espec, fa, fb)
        return acc

    @staticmethod
    def is_inflation(spec: MapSpec, prev: MapState, cur: MapState) -> jax.Array:
        # clock descends (src/lasp_lattice.erl:166-167); reset eras and
        # tombstone baselines only ever advance
        out = clock_inflation(prev.clock, cur.clock)
        if _resets(spec):
            out = out & jnp.all(prev.epochs <= cur.epochs)
            for tp, tc in zip(prev.tombs, cur.tombs):
                if tp is not None:
                    out = out & jnp.all(tp <= tc)
        return out

    @staticmethod
    def is_strict_inflation(spec: MapSpec, prev: MapState, cur: MapState) -> jax.Array:
        # src/lasp_lattice.erl:264-271 (same rule as orswot); in reset
        # mode an epoch advance under an unchanged clock (a remove whose
        # dots were already absorbed) still counts as change
        out = strict_clock_inflation(prev.clock, prev.dots, cur.clock, cur.dots)
        if _resets(spec):
            grew = jnp.any(cur.epochs > prev.epochs)
            out = out | (clock_inflation(prev.clock, cur.clock) & grew)
        return out
