"""CRDT Map: composed field lattices under observe-remove key presence.

Reference semantics (external dep ``riak_dt_map``, used by the KVS-replica
workload ``riak_test/lasp_kvs_replica_test.erl:57-135`` and ordered by the
framework at ``src/lasp_lattice.erl:166-167, 264-271``): state is
``{VClock, Entries, Deferred}`` where entries map ``{Name, Type}`` field
keys to embedded CRDTs plus presence dots; ``{update, [{update, Key, Op} |
{remove, Key}]}`` applies batched field ops; merge is OR-SWOT presence
logic over keys plus per-field embedded merge; inflation = clock descends,
strict inflation = dominating clock or equal clocks with removed fields.

Dense encoding: the field *schema is static* — a ``MapSpec`` fixes the
ordered tuple of (key, embedded codec, embedded spec) — so a Map state is
``clock: int32[A]``, ``dots: int32[F, A]`` (presence, exactly the ORSWOT
dot matrix over field slots) and a tuple of embedded states. Dense-shape
divergence (documented): the reference resets a field's contents when the
field is removed and re-added; here contents are join-monotone across
remove/re-add (presence controls visibility only) — the trade that keeps
merge a pure elementwise lattice join over fixed shapes.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .base import CrdtType
from .dots import clock_inflation, merge_dots, mint_dot, strict_clock_inflation


@dataclasses.dataclass(frozen=True)
class MapSpec:
    #: ordered static schema: ((key, codec_cls, embedded_spec), ...)
    fields: tuple
    n_actors: int

    @property
    def n_fields(self) -> int:
        return len(self.fields)

    def field_index(self, key) -> int:
        for i, (k, _c, _s) in enumerate(self.fields):
            if k == key:
                return i
        raise KeyError(f"riak_dt_map: unknown field {key!r} (static schema)")


class MapState(NamedTuple):
    clock: jax.Array  # int32[A]
    dots: jax.Array  # int32[F, A] — field-presence dots (ORSWOT logic)
    fields: tuple  # embedded states, schema order


class CrdtMap(CrdtType):
    name = "riak_dt_map"

    @staticmethod
    def new(spec: MapSpec) -> MapState:
        return MapState(
            clock=jnp.zeros((spec.n_actors,), dtype=jnp.int32),
            dots=jnp.zeros((spec.n_fields, spec.n_actors), dtype=jnp.int32),
            fields=tuple(codec.new(espec) for _k, codec, espec in spec.fields),
        )

    # -- updates ------------------------------------------------------------
    @staticmethod
    def touch(spec: MapSpec, state: MapState, field_idx: int, actor_idx) -> MapState:
        """Mark a field present with a fresh dot (the presence half of
        ``{update, Key, Op}``); the embedded op is applied by the caller."""
        clock, dots = mint_dot(state.clock, state.dots, field_idx, actor_idx)
        return MapState(clock=clock, dots=dots, fields=state.fields)

    @staticmethod
    def set_field(spec: MapSpec, state: MapState, field_idx: int, fstate) -> MapState:
        fields = list(state.fields)
        fields[field_idx] = fstate
        return MapState(clock=state.clock, dots=state.dots, fields=tuple(fields))

    @staticmethod
    def remove(spec: MapSpec, state: MapState, field_idx: int) -> MapState:
        """``{remove, Key}``: drop the presence dots; the clock witnesses
        them so merges cannot resurrect the removal."""
        return MapState(
            clock=state.clock,
            dots=state.dots.at[field_idx].set(0),
            fields=state.fields,
        )

    # -- lattice ------------------------------------------------------------
    @staticmethod
    def merge(spec: MapSpec, a: MapState, b: MapState) -> MapState:
        clock, dots = merge_dots(a.clock, a.dots, b.clock, b.dots)
        fields = tuple(
            codec.merge(espec, fa, fb)
            for (_k, codec, espec), fa, fb in zip(spec.fields, a.fields, b.fields)
        )
        return MapState(clock=clock, dots=dots, fields=fields)

    @staticmethod
    def value(spec: MapSpec, state: MapState) -> jax.Array:
        """bool[F]: field presence mask (embedded values decode host-side)."""
        return jnp.any(state.dots > 0, axis=-1)

    @staticmethod
    def equal(spec: MapSpec, a: MapState, b: MapState) -> jax.Array:
        acc = jnp.all(a.clock == b.clock) & jnp.all(a.dots == b.dots)
        for (_k, codec, espec), fa, fb in zip(spec.fields, a.fields, b.fields):
            acc = acc & codec.equal(espec, fa, fb)
        return acc

    @staticmethod
    def is_inflation(spec: MapSpec, prev: MapState, cur: MapState) -> jax.Array:
        # clock descends (src/lasp_lattice.erl:166-167)
        return clock_inflation(prev.clock, cur.clock)

    @staticmethod
    def is_strict_inflation(spec: MapSpec, prev: MapState, cur: MapState) -> jax.Array:
        # src/lasp_lattice.erl:264-271 (same rule as orswot)
        return strict_clock_inflation(prev.clock, prev.dots, cur.clock, cur.dots)
