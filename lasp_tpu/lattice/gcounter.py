"""G-Counter: per-actor monotone counts; merge = elementwise max.

Reference semantics (external dep ``riak_dt_gcounter``, accepted at
``include/lasp.hrl:76``): state is an orddict actor -> count; value is the
sum; merge takes the per-actor max. Order theory
(``src/lasp_lattice.erl:169-179``): inflation = every actor in the previous
state appears with at least the same count; strict inflation uses the total
value shortcut (:273-275).

Dense encoding: ``counts: int32[n_actors]`` — actor ids are dense writer
indices (the store layer interns arbitrary actor terms). Threshold reads
compare against a *numeric* threshold, not a state
(``src/lasp_lattice.erl:87-90``).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .base import CrdtType, Threshold


@dataclasses.dataclass(frozen=True)
class GCounterSpec:
    n_actors: int
    dtype: str = "int32"


class GCounterState(NamedTuple):
    counts: jax.Array  # dtype[n_actors]


class GCounter(CrdtType):
    name = "riak_dt_gcounter"
    leafwise_join = "max"

    @staticmethod
    def new(spec: GCounterSpec) -> GCounterState:
        return GCounterState(counts=jnp.zeros((spec.n_actors,), dtype=spec.dtype))

    @staticmethod
    def increment(
        spec: GCounterSpec, state: GCounterState, actor_idx, by=1
    ) -> GCounterState:
        """``update(increment, Actor)``; jittable scalar or vector actor ids."""
        return GCounterState(counts=state.counts.at[actor_idx].add(by))

    @staticmethod
    def increment_vector(
        spec: GCounterSpec, state: GCounterState, by: jax.Array
    ) -> GCounterState:
        """Batched device-side update: add a per-actor increment vector."""
        return GCounterState(counts=state.counts + by.astype(state.counts.dtype))

    @staticmethod
    def merge(spec: GCounterSpec, a: GCounterState, b: GCounterState) -> GCounterState:
        return GCounterState(counts=jnp.maximum(a.counts, b.counts))

    @staticmethod
    def value(spec: GCounterSpec, state: GCounterState) -> jax.Array:
        return jnp.sum(state.counts)

    @staticmethod
    def equal(spec: GCounterSpec, a: GCounterState, b: GCounterState) -> jax.Array:
        return jnp.all(a.counts == b.counts)

    @staticmethod
    def is_inflation(
        spec: GCounterSpec, prev: GCounterState, cur: GCounterState
    ) -> jax.Array:
        return jnp.all(prev.counts <= cur.counts)

    @staticmethod
    def is_strict_inflation(
        spec: GCounterSpec, prev: GCounterState, cur: GCounterState
    ) -> jax.Array:
        # total-value shortcut, mirroring src/lasp_lattice.erl:273-275
        return jnp.sum(prev.counts) < jnp.sum(cur.counts)

    @classmethod
    def threshold_met(
        cls, spec: GCounterSpec, state: GCounterState, threshold: Threshold
    ) -> jax.Array:
        """Numeric threshold per ``src/lasp_lattice.erl:87-90``: strict means
        ``threshold < value``, non-strict ``threshold <= value``."""
        total = jnp.sum(state.counts)
        thr = jnp.asarray(threshold.state)
        return jnp.where(threshold.strict, thr < total, thr <= total)
