"""Lattice layer: dense CRDT codecs + vmapped join kernels.

TPU-native rebuild of the reference data layer (SURVEY.md §2.1/§2.2):
``lasp_ivar`` / ``lasp_gset`` / ``lasp_orset`` (+ ``lasp_orset_gbtree``,
which on TPU is the *same* codec — the gbtree variant only changes the
Erlang-side data structure, ``src/lasp_orset_gbtree.erl``) and the
``riak_dt`` types accepted at ``include/lasp.hrl:76``.
"""

from .base import CrdtType, Threshold, TypeRegistry, replicate, tree_all_equal
from .gcounter import GCounter, GCounterSpec, GCounterState
from .gset import GSet, GSetSpec, GSetState
from .ivar import IVar, IVarSpec, IVarState
from .map import CrdtMap, MapSpec, MapState
from .orset import ORSet, ORSetSpec, ORSetState
from .orswot import ORSWOT, ORSWOTSpec, ORSWOTState

#: ``lasp_orset_gbtree`` is semantically identical to ``lasp_orset`` (same
#: merge :134-140 / value :67-103 contract); it exists in the reference only
#: for O(log n) host data structures, which dense tensors subsume.
ORSetGbtree = type("ORSetGbtree", (ORSet,), {"name": "lasp_orset_gbtree"})

REGISTRY = TypeRegistry(
    types=(IVar, GSet, ORSet, ORSetGbtree, GCounter, ORSWOT, CrdtMap)
)


def get_type(name: str):
    """Resolve a reference type name (e.g. ``"lasp_orset"``) to its codec."""
    return REGISTRY.get(name)


__all__ = [
    "CrdtType",
    "Threshold",
    "TypeRegistry",
    "replicate",
    "tree_all_equal",
    "IVar",
    "IVarSpec",
    "IVarState",
    "GSet",
    "GSetSpec",
    "GSetState",
    "ORSet",
    "ORSetGbtree",
    "ORSetSpec",
    "ORSetState",
    "GCounter",
    "GCounterSpec",
    "GCounterState",
    "ORSWOT",
    "ORSWOTSpec",
    "ORSWOTState",
    "CrdtMap",
    "MapSpec",
    "MapState",
    "REGISTRY",
    "get_type",
]
