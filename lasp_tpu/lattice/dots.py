"""Shared dot-matrix algebra for the vclock-based types (ORSWOT, Map).

Both ``riak_dt_orswot`` and ``riak_dt_map`` track presence with birth dots
under a vector clock and share one merge/order rule
(``src/lasp_lattice.erl:163-167, 255-271`` applies the identical logic to
both); this module is that rule, written once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def merge_dots(clock_a, dots_a, clock_b, dots_b):
    """Join two (clock, dots) pairs. A dot survives iff present on both
    sides (still live everywhere) or present on one side and UNSEEN by the
    other's clock (a newer add that side hasn't learned; a seen-but-absent
    dot was removed). Returns (clock, dots)."""
    clock = jnp.maximum(clock_a, clock_b)
    keep_a = (dots_a > 0) & ((dots_a == dots_b) | (dots_a > clock_b[None, :]))
    keep_b = (dots_b > 0) & ((dots_b == dots_a) | (dots_b > clock_a[None, :]))
    dots = jnp.maximum(
        jnp.where(keep_a, dots_a, 0), jnp.where(keep_b, dots_b, 0)
    )
    return clock, dots


def clock_inflation(prev_clock, cur_clock) -> jax.Array:
    """vclock descends (``src/lasp_lattice.erl:163-167``)."""
    return jnp.all(prev_clock <= cur_clock)


def strict_clock_inflation(prev_clock, prev_dots, cur_clock, cur_dots) -> jax.Array:
    """``src/lasp_lattice.erl:255-271``: inflation ∧ (equal clocks with
    fewer present entries — a removal — or strictly dominating clock)."""
    inflation = clock_inflation(prev_clock, cur_clock)
    equal_clocks = jnp.all(prev_clock == cur_clock)
    dominates = inflation & jnp.any(cur_clock > prev_clock)
    deleted = jnp.sum(jnp.any(cur_dots > 0, axis=-1)) < jnp.sum(
        jnp.any(prev_dots > 0, axis=-1)
    )
    return inflation & ((equal_clocks & deleted) | dominates)


def mint_dot(clock, dots, entry_idx, actor_idx):
    """Advance the actor's clock and replace the entry's dots with the
    fresh single dot (the shared ``add``/``touch`` move). Returns
    (clock, dots)."""
    counter = clock[actor_idx] + 1
    clock = clock.at[actor_idx].set(counter)
    row = jnp.zeros_like(clock).at[actor_idx].set(counter)
    return clock, dots.at[entry_idx].set(row)
