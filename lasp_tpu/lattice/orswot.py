"""OR-SWOT: observe-remove set WithOut Tombstones, as (vclock, dot-matrix).

Reference semantics (external dep ``riak_dt_orswot``, accepted at
``include/lasp.hrl:76``; order theory consumed by the framework at
``src/lasp_lattice.erl:163-167, 255-262``): state is ``{VClock, Entries,
Deferred}`` where each present element carries a minimal *dot* list (actor,
event-counter); ``add`` advances the actor's clock and replaces the
element's dots with the new single dot; ``remove`` drops the entry outright
(no tombstone — the clock remembers); ``merge`` keeps a dot iff both sides
have it, or one side has it and the other's clock has not yet seen it
(i.e. the dot is newer than that clock, so it cannot have been removed).

Dense encoding: ``clock: int32[A]`` (vector clock = per-actor max event)
and ``dots: int32[E, A]`` (0 = no dot; else the event counter of the add).
One dot per (element, actor) — exactly what our ``add`` mints (it replaces
the element's dots, as the reference does), and what merges preserve.

Order theory (the predicates the framework actually uses):
``is_inflation`` = clock descends (``src/lasp_lattice.erl:163-164``);
``is_strict_inflation`` = inflation ∧ (equal clocks with fewer elements —
a removal — or strictly dominating clock) (:255-262); ``threshold_met``
defaults to the inflation pair like the other set types (:77-80).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .base import CrdtType
from .dots import clock_inflation, merge_dots, mint_dot, strict_clock_inflation


@dataclasses.dataclass(frozen=True)
class ORSWOTSpec:
    n_elems: int
    n_actors: int


class ORSWOTState(NamedTuple):
    clock: jax.Array  # int32[A] — per-actor max event counter
    dots: jax.Array  # int32[E, A] — birth dot of each live element, 0 = none


class ORSWOT(CrdtType):
    name = "riak_dt_orswot"

    @staticmethod
    def new(spec: ORSWOTSpec) -> ORSWOTState:
        return ORSWOTState(
            clock=jnp.zeros((spec.n_actors,), dtype=jnp.int32),
            dots=jnp.zeros((spec.n_elems, spec.n_actors), dtype=jnp.int32),
        )

    # -- updates ------------------------------------------------------------
    @staticmethod
    def add(spec: ORSWOTSpec, state: ORSWOTState, elem_idx, actor_idx) -> ORSWOTState:
        """``update({add, E}, Actor)``: bump the actor's clock, replace the
        element's dots with the fresh single dot (riak_dt_orswot add)."""
        clock, dots = mint_dot(state.clock, state.dots, elem_idx, actor_idx)
        return ORSWOTState(clock=clock, dots=dots)

    @staticmethod
    def remove(spec: ORSWOTSpec, state: ORSWOTState, elem_idx) -> ORSWOTState:
        """``update({remove, E})``: drop the entry; the clock already
        witnesses its dots, so merges cannot resurrect it."""
        return ORSWOTState(
            clock=state.clock,
            dots=state.dots.at[elem_idx].set(0),
        )

    # -- lattice ------------------------------------------------------------
    @staticmethod
    def merge(spec: ORSWOTSpec, a: ORSWOTState, b: ORSWOTState) -> ORSWOTState:
        """See :func:`lasp_tpu.lattice.dots.merge_dots` for the survival
        rule (shared with riak_dt_map)."""
        clock, dots = merge_dots(a.clock, a.dots, b.clock, b.dots)
        return ORSWOTState(clock=clock, dots=dots)

    @staticmethod
    def value(spec: ORSWOTSpec, state: ORSWOTState) -> jax.Array:
        """bool[E]: element holds at least one live dot."""
        return jnp.any(state.dots > 0, axis=-1)

    @staticmethod
    def member_mask(spec: ORSWOTSpec, state: ORSWOTState) -> jax.Array:
        return jnp.any(state.dots > 0, axis=-1)

    @staticmethod
    def equal(spec: ORSWOTSpec, a: ORSWOTState, b: ORSWOTState) -> jax.Array:
        return jnp.all(a.clock == b.clock) & jnp.all(a.dots == b.dots)

    @staticmethod
    def is_inflation(spec: ORSWOTSpec, prev: ORSWOTState, cur: ORSWOTState) -> jax.Array:
        return clock_inflation(prev.clock, cur.clock)

    @staticmethod
    def is_strict_inflation(
        spec: ORSWOTSpec, prev: ORSWOTState, cur: ORSWOTState
    ) -> jax.Array:
        return strict_clock_inflation(prev.clock, prev.dots, cur.clock, cur.dots)

    @staticmethod
    def stats(spec: ORSWOTSpec, state: ORSWOTState) -> dict:
        return {
            "element_count": int(jnp.sum(jnp.any(state.dots > 0, axis=-1))),
            "clock_total": int(jnp.sum(state.clock)),
        }
