"""I-Var: single-assignment variable as a (defined, payload) tensor pair.

Reference semantics (``src/lasp_ivar.erl``): bottom is ``undefined``
(``new/0`` :41-43), ``update({set, V})`` binds once (:45-47), merge is
"defined wins" with idempotent double-bind of the same value (:50-56).
Order theory (``src/lasp_lattice.erl:126-135, 204-210``): any state inflates
``undefined``; two defined states are ordered only if equal; strict inflation
is exactly the undefined→defined transition.

Dense encoding: ``defined: bool[]`` plus ``value: int32[]`` holding an
interned payload id (the store layer maps arbitrary Python payloads to dense
ids, replacing druuid/crypto-generated identity in the reference — see
SURVEY.md §2.4 native-code census). Conflicting concurrent binds (undefined
behaviour in the reference — ``merge(A, A)`` has no clause for ``A =/= B``,
``src/lasp_ivar.erl:50-56``) deterministically resolve to the max payload id
so that merge stays total, commutative, and associative on TPU.

Note on the conflict case: the order predicates keep the *reference* partial
order (two defined values are comparable only when equal), so after a
conflicting merge the result does not inflate the losing side. This mirrors
the reference exactly: there a conflicting merge raises and the write is
swallowed by the bind path (``src/lasp_core.erl:308-311``), leaving the
replica on its old value — here the inflation gate rejects the same write.
Un-gated gossip merges instead converge deterministically to the max payload
(where the reference would crash the gossip process).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .base import CrdtType, Threshold


@dataclasses.dataclass(frozen=True)
class IVarSpec:
    """I-Vars need no capacities; kept for interface uniformity."""

    dtype: str = "int32"


class IVarState(NamedTuple):
    defined: jax.Array  # bool[]
    value: jax.Array  # dtype[] — interned payload id


class IVar(CrdtType):
    name = "lasp_ivar"

    @staticmethod
    def new(spec: IVarSpec) -> IVarState:
        return IVarState(
            defined=jnp.zeros((), dtype=bool),
            value=jnp.zeros((), dtype=spec.dtype),
        )

    @staticmethod
    def set(spec: IVarSpec, state: IVarState, payload_id) -> IVarState:
        """``update({set, V})`` — bind the variable (``src/lasp_ivar.erl:45-47``).

        Jittable; binding an already-defined ivar keeps the existing value
        (single assignment), matching the reference where re-bind of a
        different value is rejected upstream by the inflation gate
        (``src/lasp_core.erl:301-306``).
        """
        payload_id = jnp.asarray(payload_id, dtype=spec.dtype)
        return IVarState(
            defined=jnp.ones((), dtype=bool) | state.defined,
            value=jnp.where(state.defined, state.value, payload_id),
        )

    @staticmethod
    def merge(spec: IVarSpec, a: IVarState, b: IVarState) -> IVarState:
        both = a.defined & b.defined
        value = jnp.where(
            both,
            jnp.maximum(a.value, b.value),
            jnp.where(a.defined, a.value, b.value),
        )
        return IVarState(defined=a.defined | b.defined, value=value)

    @staticmethod
    def value(spec: IVarSpec, state: IVarState):
        return state

    @staticmethod
    def equal(spec: IVarSpec, a: IVarState, b: IVarState) -> jax.Array:
        values_match = jnp.logical_or(
            ~(a.defined & b.defined), a.value == b.value
        )
        return (a.defined == b.defined) & values_match

    @staticmethod
    def is_inflation(spec: IVarSpec, prev: IVarState, cur: IVarState) -> jax.Array:
        # undefined <= anything; defined states comparable only when equal
        # (src/lasp_lattice.erl:126-135).
        return ~prev.defined | (cur.defined & (prev.value == cur.value))

    @staticmethod
    def is_strict_inflation(
        spec: IVarSpec, prev: IVarState, cur: IVarState
    ) -> jax.Array:
        # exactly undefined -> defined (src/lasp_lattice.erl:204-210)
        return ~prev.defined & cur.defined

    @classmethod
    def threshold_met(
        cls, spec: IVarSpec, state: IVarState, threshold: Threshold
    ) -> jax.Array:
        """Equality-style threshold per ``src/lasp_lattice.erl:51-60``:
        ``{strict, undefined}`` means "became defined"; otherwise the value
        must equal the threshold exactly (undefined == undefined included)."""
        thr: IVarState = threshold.state
        if threshold.strict:
            # met iff threshold is undefined and the value is defined
            return ~thr.defined & state.defined
        same_definedness = thr.defined == state.defined
        values_match = jnp.logical_or(~thr.defined, thr.value == state.value)
        return same_definedness & values_match
