"""G-Set: grow-only set as a boolean membership mask over a fixed universe.

Reference semantics (``src/lasp_gset.erl``): state is an ``ordsets`` list,
``update {add|add_all}`` inserts (:84-93), merge is set union (:99-101).
Order theory: inflation = subset (``src/lasp_lattice.erl:137-140``), strict
inflation additionally requires a new element (:212-215).

Dense encoding: ``mask: bool[n_elems]`` over a per-variable element universe
(host-side interning lives in the store layer). Merge is elementwise OR — a
single VPU op vmapped over replicas, and a valid ``all_reduce`` operator for
quorum/anti-entropy collectives.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .base import CrdtType


@dataclasses.dataclass(frozen=True)
class GSetSpec:
    n_elems: int


class GSetState(NamedTuple):
    mask: jax.Array  # bool[n_elems]


class GSet(CrdtType):
    name = "lasp_gset"
    leafwise_join = "or"

    @staticmethod
    def new(spec: GSetSpec) -> GSetState:
        return GSetState(mask=jnp.zeros((spec.n_elems,), dtype=bool))

    @staticmethod
    def add(spec: GSetSpec, state: GSetState, elem_idx) -> GSetState:
        """``update({add, Elem})`` (``src/lasp_gset.erl:84-87``). Jittable;
        ``elem_idx`` may be a scalar or an index vector (add_all)."""
        mask = state.mask.at[elem_idx].set(True)
        return GSetState(mask=mask)

    @staticmethod
    def add_mask(spec: GSetSpec, state: GSetState, add: jax.Array) -> GSetState:
        """Batched ``add_all`` from a boolean mask — the device-side update
        kernel for large simulations."""
        return GSetState(mask=state.mask | add)

    @staticmethod
    def merge(spec: GSetSpec, a: GSetState, b: GSetState) -> GSetState:
        return GSetState(mask=a.mask | b.mask)

    @staticmethod
    def value(spec: GSetSpec, state: GSetState) -> jax.Array:
        return state.mask

    @staticmethod
    def equal(spec: GSetSpec, a: GSetState, b: GSetState) -> jax.Array:
        return jnp.all(a.mask == b.mask)

    @staticmethod
    def is_inflation(spec: GSetSpec, prev: GSetState, cur: GSetState) -> jax.Array:
        return jnp.all(~prev.mask | cur.mask)

    @staticmethod
    def is_strict_inflation(
        spec: GSetSpec, prev: GSetState, cur: GSetState
    ) -> jax.Array:
        inflation = jnp.all(~prev.mask | cur.mask)
        grew = jnp.any(cur.mask & ~prev.mask)
        return inflation & grew

    @staticmethod
    def stats(spec: GSetSpec, state: GSetState) -> dict:
        # element_count per src/lasp_gset.erl:130-142
        return {"element_count": int(jnp.sum(state.mask))}
