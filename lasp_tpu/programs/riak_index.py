"""Materialized 2i index views — the reference's one non-trivial program.

Rebuild of ``src/lasp_riak_index_program.erl`` (:59-176) and the
``lasp_transform`` parameterization machinery (``src/lasp_transform.erl:
32-128``): a riak_kv-style secondary-index materialized view over an
OR-Set, fed object-change notifications.

Semantics (reference lines in parentheses):

- on ``put``: remove any stale entries for the object's key (:67-68,
  remove-then-add), then add ``(key, metadata)`` keyed by a token DERIVED
  FROM THE COORDINATOR'S VCLOCK (:146-149) — the same logical write mints
  the same token on every replica, so cross-replica merges of the view
  are idempotent;
- a *total* index (no index name) indexes every object (:71-74); a
  *subset view* indexes only objects whose index specs carry a matching
  ``(add, name, value)`` entry (:75-89);
- the top-level index auto-registers one parameterized sub-view per index
  spec it observes (:92-98, ``create_views`` :162-176);
- on ``delete``: remove the key's entries (:102-104); ``handoff``
  (:105-107 is a TODO in the reference) RE-INDEXES idempotently — a
  handoff notification re-describes an object whose entries the
  receiving instance may never have seen, so a key with NO live entry
  takes the put path and an already-indexed key is left untouched (a
  handoff frame carries no ordering authority; the vclock-derived
  token keeps the replay merge-idempotent);
- ``execute`` streams the set; ``value`` projects keys only (:117-121).

Where the reference needs a parse_transform + per-vnode recompilation to
stamp ``(module, index_name, index_value)`` into a copy of the source
(``src/lasp_transform.erl:111-128``, applied at ``src/lasp_vnode.erl:
294-331``) — because BEAM parameterizes code by generating modules — the
TPU build parameterizes by CONSTRUCTION: a view is an instance of this
class with ``index_name``/``index_value`` set, registered under the same
derived name the reference would generate. No runtime compiler, same
many-instances-of-one-source capability.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Optional

from .base import Program

#: the reference's base module name; derived view names append -name-value
#: exactly like create_views' list_to_atom (:164-166)
BASE_NAME = "lasp_riak_index_program"


@dataclasses.dataclass(frozen=True)
class RiakObject:
    """The slice of a riak_object the program reads (:60-63): key, the
    coordinator's vclock, opaque metadata, and 2i index specs — an
    iterable of ``(op, index_name, index_value)`` tuples."""

    key: Any
    vclock: Any
    metadata: Any = None
    index_specs: tuple = ()


def view_name(index_name: str, index_value: str) -> str:
    return f"{BASE_NAME}-{index_name}-{index_value}"


class RiakIndexProgram(Program):
    type_name = "lasp_orset_gbtree"

    def __init__(
        self,
        index_name: Optional[str] = None,
        index_value: Optional[str] = None,
        n_elems: int = 64,
        token_space: int = 64,
        auto_views: bool = True,
    ):
        self.index_name = index_name
        self.index_value = index_value
        self.n_elems = n_elems
        self.token_space = token_space
        self.auto_views = auto_views
        self.id: Optional[str] = None

    @property
    def name(self) -> str:
        if self.index_name is None:
            return BASE_NAME
        return view_name(self.index_name, self.index_value)

    def init(self, session) -> None:
        # one accumulator OR-Set per instance, named like the generated
        # module (the normalize_to_binary'd Id of :53-55)
        self.id = session.declare(
            type=self.type_name,
            id=self.name,
            n_elems=self.n_elems,
            n_actors=1,
            tokens_per_actor=self.token_space,
        )

    # -- event hook ----------------------------------------------------------
    def process(self, session, object, reason, actor) -> None:
        obj = object if isinstance(object, RiakObject) else RiakObject(*object)
        # only additive specs create/select views (:168-173)
        specs = [s for s in obj.index_specs if s[0] == "add"]
        if reason == "put":
            self._remove_entries_for_key(session, obj.key, actor)
            if self.index_name is None:
                self._add_entry(session, obj, actor)
            else:
                for _op, name, value in specs:
                    if name == self.index_name and value == self.index_value:
                        self._add_entry(session, obj, actor)
            if self.index_name is None and self.auto_views:
                self._create_views(session, specs)
        elif reason == "delete":
            self._remove_entries_for_key(session, obj.key, actor)
        elif reason == "handoff":
            # ownership moved: the notification RE-DESCRIBES an object
            # the receiving instance may never have indexed (:105-107
            # leaves this as a TODO in the reference). Re-index
            # IDEMPOTENTLY, gated PER KEY: only a key with NO live
            # entry takes the put path. A key that already has an
            # opinion — this exact write, or any other version — is
            # left alone: the put path is the sole authority on
            # ordering, and a handoff frame carries none (running the
            # put path for a STALE re-description would remove the
            # newer live entry, whose tombstoned token then suppresses
            # every later replay — the entry would be unrecoverable).
            # Replaying the same handoff is a no-op (the key is now
            # indexed), and a handoff after a delete of the SAME write
            # stays deleted: the re-add lands on its own tombstoned
            # vclock-derived token.
            if not self._key_indexed(session, obj.key):
                self.process(session, obj, "put", actor)
        else:
            # an unrecognized reason is a caller bug (a misspelled verb
            # would otherwise drop the notification silently — an index
            # that quietly misses writes is worse than a crash)
            raise NotImplementedError(
                f"{self.name}: unsupported object-event reason {reason!r} "
                "(expected 'put', 'delete', or 'handoff')"
            )

    # -- results -------------------------------------------------------------
    def execute(self, session):
        """Live ``(key, metadata)`` entries. Stored elements additionally
        carry the full vclock digest (see :meth:`_add_entry`); it is an
        internal identity component, stripped here."""
        return {(key, metadata) for key, metadata, _digest in
                session.value(self.id)}

    def value(self, output):
        """Keys only, not metadata (:119-121)."""
        return {key for key, _metadata in output}

    # -- internals -----------------------------------------------------------
    def _key_indexed(self, session, key) -> bool:
        """Does the view hold ANY live entry for ``key``? The handoff
        idempotence gate: an indexed key already has an opinion (this
        version or another), and only the put path — which carries
        ordering authority — may replace it."""
        return any(e[0] == key for e in session.value(self.id))

    def _remove_entries_for_key(self, session, key, actor) -> None:
        """Remove every (key, *) entry currently in the view (:127-139)."""
        stale = [e for e in session.value(self.id) if e[0] == key]
        if stale:
            session.store.update(self.id, ("remove_all", stale), actor)

    def compact(self, session) -> int:
        """Reclaim element slots held by fully-tombstoned entries.

        Every distinct write interns a fresh ``(key, metadata, digest)``
        element, and remove-stale only tombstones tokens — so the element
        universe fills with dead entries over the view's lifetime (the
        ``waste_pct`` the reference reports but never reclaims,
        ``src/lasp_orset.erl:178-191``). Dropping an element row is safe
        because the view variable is program-private: under a single-store
        session nothing else holds its state, and under mesh delivery
        ``MeshSession``'s compact converges the population to divergence 0
        first, so the uniform reindex covers every replica row that could
        reintroduce the tombstones. (The one observable difference: a
        byte-identical
        replay of a write whose entry was deleted AND compacted re-indexes
        the key; without compaction the tombstone suppresses it.) Live
        rows are kept verbatim, including their tombstoned tokens.

        Returns the number of slots reclaimed."""
        return session.store.compact_orset(self.id)

    def _add_entry(self, session, obj: RiakObject, actor) -> None:
        """Entry keyed by the hashed coordinator vclock (:141-149), so the
        same logical write is idempotent across replicas while distinct
        writes never collide.

        The reference uses the raw 16-byte md5 as the OR-Set token; a
        dense token space is bounded, so folding the digest to
        ``% token_space`` alone would let two DIFFERENT vclocks collide
        (~1/token_space per delete/re-put cycle) — and a collision with a
        tombstoned token is silently suppressed by the merge gate
        (``src/lasp_orset.erl:128-134``), dropping an acknowledged write.
        Instead the FULL 128-bit digest rides in the element identity
        ``(key, metadata, digest)``: distinct writes occupy distinct
        element rows (fresh token planes, no cross-write collisions), and
        a byte-identical replay lands on the same element + token —
        idempotent, and still tombstone-suppressed after a delete, exactly
        like the reference."""
        from ..utils.interning import CapacityError

        digest = hashlib.md5(repr(obj.vclock).encode()).digest()
        token = int.from_bytes(digest[:8], "little") % self.token_space
        op = (
            "add_by_token",
            token,
            (obj.key, obj.metadata, int.from_bytes(digest, "little")),
        )
        try:
            session.store.update(self.id, op, actor)
        except CapacityError:
            # dead entries exhaust the universe over the view's lifetime;
            # reclaim them and retry — only a genuinely-full LIVE view
            # stays loud
            if self.compact(session) == 0:
                raise
            session.store.update(self.id, op, actor)

    def _create_views(self, session, specs) -> None:
        """Register one parameterized sub-view per observed index spec
        (:162-176). ``session.register`` is idempotent, mirroring the
        reference's fire-and-forget spawn ("if this fails ... it will be
        generated on the next write")."""
        for _op, name, value in specs:
            session.register(
                view_name(name, value),
                RiakIndexProgram,
                index_name=name,
                index_value=value,
                n_elems=self.n_elems,
                token_space=self.token_space,
                auto_views=False,
            )
