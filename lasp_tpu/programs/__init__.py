"""Programs layer (L5): distributed incremental programs.

Rebuild of the ``lasp_program`` behaviour (``src/lasp_program.erl:29-46``):
``init/1, process/5, execute/2, value/1, type/0``. The reference compiles
program source on every partition and hot-loads it (``src/lasp_vnode.erl:
276-366``) because BEAM ships code at runtime; here a program is a plain
Python class traced into the session's jitted rounds — no deployment step.
"""

from .base import Program
from .examples import ExampleKeylistProgram, ExampleProgram
from .riak_index import RiakIndexProgram, RiakObject, view_name

__all__ = [
    "Program",
    "ExampleProgram",
    "ExampleKeylistProgram",
    "RiakIndexProgram",
    "RiakObject",
    "view_name",
]
