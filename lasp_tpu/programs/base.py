"""The program behaviour contract (``src/lasp_program.erl:29-46``)."""

from __future__ import annotations


class Program:
    """Base class mirroring the ``lasp_program`` behaviour callbacks.

    Lifecycle: ``init`` declares whatever variables the program owns;
    ``process`` receives object-change notifications (the riak_kv
    put/delete/handoff hook path, ``src/lasp.erl:129-150``); ``execute``
    returns the current result; ``value`` post-filters it; ``type`` names
    the result CRDT."""

    #: result CRDT type (``type/0``)
    type_name: str = "lasp_orset"

    def init(self, session) -> None:
        """``init/1``: declare owned variables against the session."""
        raise NotImplementedError

    def process(self, session, object, reason, actor) -> None:
        """``process/5``: fold one object event into program state."""
        raise NotImplementedError

    def execute(self, session):
        """``execute/2``: current result (decoded value)."""
        raise NotImplementedError

    def value(self, output):
        """``value/1``: optional post-filter; identity by default."""
        return output
