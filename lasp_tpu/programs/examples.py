"""The reference's built-in example programs, rebuilt.

- :class:`ExampleProgram` — OR-set accumulator of every notified object
  (``src/lasp_example_program.erl:38-61``; its internal type
  ``lasp_orset_gbtree`` is codec-identical to ``lasp_orset`` here).
- :class:`ExampleKeylistProgram` — G-set of keys seen
  (``src/lasp_example_keylist_program.erl:38-60``).
"""

from __future__ import annotations

from .base import Program


class ExampleProgram(Program):
    type_name = "lasp_orset_gbtree"

    def __init__(self, n_elems: int = 64):
        self.n_elems = n_elems
        self.id = None

    def init(self, session) -> None:
        self.id = session.declare(type=self.type_name, n_elems=self.n_elems)

    def process(self, session, object, reason, actor) -> None:
        # every event adds the object (src/lasp_example_program.erl:43-45)
        session.store.update(self.id, ("add", object), actor)

    def execute(self, session):
        return session.value(self.id)


class ExampleKeylistProgram(Program):
    type_name = "lasp_gset"

    def __init__(self, n_elems: int = 64):
        self.n_elems = n_elems
        self.id = None

    def init(self, session) -> None:
        self.id = session.declare(type=self.type_name, n_elems=self.n_elems)

    def process(self, session, object, reason, actor) -> None:
        # object events carry (key, value); the keylist keeps keys
        # (src/lasp_example_keylist_program.erl:43-45)
        key = object[0] if isinstance(object, tuple) else object
        session.store.update(self.id, ("add", key), actor)

    def execute(self, session):
        return session.value(self.id)
