"""Command-line console (reference L6: ``bin/lasp``/``lasp-admin`` +
``lasp_console``, SURVEY.md §1/§2.7). Cluster-admin verbs map to their
simulation equivalents: ``status`` (ringready/member-status) reports
devices and convergence state; ``simulate`` runs a gossip population to
its fixed point; ``bench`` runs the BASELINE scenarios; ``metrics``
prints a telemetry snapshot (Prometheus text + optional JSONL; the
riak-admin ``status``/``stat`` role — see docs/OBSERVABILITY.md);
``top`` is the live cluster-health view (per-var residual/staleness/
lag, shard lag, alerts — the convergence observatory); ``trace``
exports a variable's causal event history as Perfetto/Chrome-trace
JSON; ``inspect`` lists a checkpoint's contents.

Usage: ``python -m lasp_tpu.cli <verb> [options]``
"""

from __future__ import annotations

import argparse
import json
import sys


def cmd_status(args) -> int:
    import jax

    import lasp_tpu

    info = {
        "version": lasp_tpu.__version__,
        "jax": jax.__version__,
        "platform": jax.devices()[0].platform,
        "devices": [str(d) for d in jax.devices()],
    }
    print(json.dumps(info, indent=2))
    return 0


def cmd_simulate(args) -> int:
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, random_regular, ring, scale_free
    from lasp_tpu.store import Store

    topo = {"ring": ring, "random": random_regular, "scale_free": scale_free}[
        args.topology
    ]
    store = Store(n_actors=max(16, args.writers))
    if args.type == "riak_dt_gcounter":
        var = store.declare(type=args.type)
        op = ("increment",)
    else:
        var = store.declare(type=args.type, n_elems=args.elems)
        op = None
    rt = ReplicatedRuntime(
        store, Graph(store), args.replicas, topo(args.replicas, args.fanout)
    )
    # one batched dispatch for all client writes, not a per-op host loop
    rt.update_batch(
        var,
        [
            ((w * args.replicas) // args.writers,
             op or ("add", f"item{w}"), f"writer{w}")
            for w in range(args.writers)
        ],
    )
    from lasp_tpu.config import get_config

    rounds = rt.run_to_convergence(
        max_rounds=args.max_rounds, block=get_config().fused_block
    )
    out = {
        "replicas": args.replicas,
        "topology": args.topology,
        "rounds_to_convergence": rounds,
        "seconds": round(rt.trace.total_seconds, 4),
        "residual_path": [r["residual"] for r in rt.trace.rounds],
    }
    # set-like types report a cardinality; the G-Counter reports its
    # numeric value under its own key (consumers parsing value_size as a
    # cardinality must never misread a counter total)
    if args.type == "riak_dt_gcounter":
        out["value"] = rt.coverage_value(var)
    else:
        out["value_size"] = len(rt.coverage_value(var))
    print(json.dumps(out))
    return 0


def cmd_chaos(args) -> int:
    """Chaos soak (the nemesis verb): run a seeded gossip population
    through a preset fault timeline, measure recovery, and verify the
    convergence-under-failure invariants — healed fixed point
    bit-identical to a fault-free twin's, monotone inflation, replay
    determinism (docs/RESILIENCE.md)."""
    from lasp_tpu.chaos import nemesis, run_harness
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import random_regular, ring, scale_free
    from lasp_tpu.mesh.runtime import ReplicatedRuntime
    from lasp_tpu.store import Store
    from lasp_tpu.telemetry import get_monitor

    topo = {"ring": ring, "random": random_regular,
            "scale_free": scale_free}[args.topology]
    nbrs = topo(args.replicas, args.fanout)

    def build():
        store = Store(n_actors=max(16, args.writers))
        var = store.declare(type=args.type, n_elems=args.elems, id="soak")
        rt = ReplicatedRuntime(store, Graph(store), args.replicas, nbrs)
        rt.update_batch(
            var,
            [
                ((w * args.replicas) // args.writers,
                 ("add", f"item{w}"), f"writer{w}")
                for w in range(args.writers)
            ],
        )
        return rt

    schedule = nemesis(
        args.preset, args.replicas, nbrs, seed=args.seed,
        rounds=args.rounds,
    )
    report = run_harness(
        build, schedule, mode=args.mode, max_rounds=args.max_rounds,
        replay=not args.no_replay,
    )
    report["preset"] = args.preset
    report["topology"] = args.topology
    report["replicas"] = args.replicas
    report["schedule"] = schedule.describe()
    report["chaos_health"] = get_monitor().health().get("chaos")
    print(json.dumps(report))
    return 0


def cmd_quorum(args) -> int:
    """Quorum coordination soak: drive a batched put/get quorum
    workload (N=3, R=W=2 by default) through a nemesis preset and
    verify the no-acknowledged-write-lost invariant (hinted handoff)
    plus replay determinism — the coordination-layer twin of the
    ``chaos`` verb (docs/RESILIENCE.md "Quorum coordination")."""
    from lasp_tpu.chaos import nemesis
    from lasp_tpu.chaos.invariants import run_quorum_harness
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import random_regular, ring, scale_free
    from lasp_tpu.mesh.runtime import ReplicatedRuntime
    from lasp_tpu.store import Store
    from lasp_tpu.telemetry import get_monitor

    topo = {"ring": ring, "random": random_regular,
            "scale_free": scale_free}[args.topology]
    nbrs = topo(args.replicas, args.fanout)

    def build():
        store = Store(n_actors=max(64, args.writes))
        store.declare(id="kv", type="lasp_gset",
                      n_elems=max(64, 2 * args.writes))
        return ReplicatedRuntime(store, Graph(store), args.replicas, nbrs)

    schedule = nemesis(
        args.preset, args.replicas, nbrs, seed=args.seed,
        rounds=args.rounds,
    )
    writes = [
        (i % max(1, args.rounds), "kv", ("add", f"k{i}"), f"c{i}",
         (i * 7) % args.replicas)
        for i in range(args.writes)
    ]
    reads = [
        (1 + i % max(1, args.rounds), "kv", (i * 11) % args.replicas)
        for i in range(args.reads)
    ]
    if args.prune_hints and not args.hints:
        # the harness's in-memory log dies with this process: pruning
        # it would report 0 while inspecting nothing
        print("error: --prune-hints needs --hints PATH (only a "
              "durable hint log outlives the harness to be reclaimed)",
              file=sys.stderr)
        return 2
    report = run_quorum_harness(
        build, schedule, writes=writes, reads=reads,
        n=args.n, r=args.r, w=args.w, timeout=args.timeout,
        retries=args.retries, engine=args.engine,
        hints_path=args.hints,
        replay=not args.no_replay,
    )
    if args.prune_hints:
        # the harness's own convergence check just proved the
        # population absorbed every hinted write — the documented safe
        # point for a FULL reclaim (per-record reclaim already runs on
        # every restore via QuorumRuntime's prune_replayed wiring)
        from lasp_tpu.quorum import HintLog

        report["hints_pruned"] = HintLog(args.hints).prune()
    report["preset"] = args.preset
    report["topology"] = args.topology
    report["replicas"] = args.replicas
    report["quorum_health"] = get_monitor().health().get("quorum")
    print(json.dumps(report))
    return 0


def cmd_aae(args) -> int:
    """Active anti-entropy drill (the scrub verb): inject silent
    corruption via a corruption-class nemesis preset, run the Merkle
    hash forest + exchange + quorum repair per round, and verify
    detection/localization/repair plus bit-equality with a fault-free
    twin (docs/RESILIENCE.md "Active anti-entropy")."""
    from lasp_tpu.chaos import nemesis
    from lasp_tpu.chaos.invariants import run_aae_harness
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import random_regular, ring, scale_free
    from lasp_tpu.mesh.runtime import ReplicatedRuntime
    from lasp_tpu.store import Store
    from lasp_tpu.telemetry import get_monitor

    topo = {"ring": ring, "random": random_regular,
            "scale_free": scale_free}[args.topology]
    nbrs = topo(args.replicas, args.fanout)

    def build():
        store = Store(n_actors=max(16, args.writers))
        var = store.declare(type=args.type, n_elems=args.elems,
                            id="scrub")
        rt = ReplicatedRuntime(store, Graph(store), args.replicas, nbrs)
        rt.update_batch(
            var,
            [
                ((w * args.replicas) // args.writers,
                 ("add", f"item{w}"), f"writer{w}")
                for w in range(args.writers)
            ],
        )
        return rt

    schedule = nemesis(
        args.preset, args.replicas, nbrs, seed=args.seed,
        rounds=args.rounds,
    )
    try:
        report = run_aae_harness(
            build, schedule, scrub_every=args.scrub_every,
            seg_size=args.seg_size, max_rounds=args.max_rounds,
            mode=args.mode, replay=not args.no_replay,
        )
    except ValueError as exc:  # e.g. dense + scrub_every > 1
        print(f"error: {exc}", file=sys.stderr)
        return 2
    report["preset"] = args.preset
    report["topology"] = args.topology
    report["replicas"] = args.replicas
    report["schedule"] = schedule.describe()
    report["aae_health"] = get_monitor().health().get("aae")
    print(json.dumps(report))
    return 0


def cmd_serve(args) -> int:
    """Serving-front-end soak: an open-loop simulated client fleet
    (write+read+watch mix, Zipf-hot keys, optional composite nemesis +
    overload burst) against the coalescing/vectorized front-end, with
    the no-acked-write-lost invariant and threshold fan-out parity
    asserted in-run (docs/SERVING.md)."""
    from lasp_tpu.serve.harness import run_load
    from lasp_tpu.telemetry import get_monitor

    report = run_load(
        n_replicas=args.replicas,
        n_clients=args.clients,
        ticks=args.ticks,
        arrivals_per_tick=args.arrivals,
        chaos=not args.no_chaos,
        burst_at=args.ticks // 2 if args.burst > 1 else None,
        burst_factor=args.burst,
        seed=args.seed,
        seed_watches=args.watches,
        parity_thresholds=args.parity,
    )
    report["serve_health"] = get_monitor().health().get("serve")
    print(json.dumps(report))
    return 0


def cmd_bench(args) -> int:
    import os
    import runpy

    if args.replicas:
        os.environ["LASP_BENCH_REPLICAS"] = str(args.replicas)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runpy.run_path(os.path.join(repo_root, "bench.py"), run_name="__main__")
    return 0


def cmd_scenario(args) -> int:
    import inspect

    from lasp_tpu.bench_scenarios import SCENARIOS

    fn = SCENARIOS[args.name]
    kwargs = {}
    if args.replicas:
        if "n_replicas" not in inspect.signature(fn).parameters:
            print(
                f"error: scenario {args.name!r} has a fixed population; "
                "--replicas is not applicable",
                file=sys.stderr,
            )
            return 2
        kwargs["n_replicas"] = args.replicas
    print(json.dumps(fn(**kwargs)))
    return 0


def cmd_bridge(args) -> int:
    """Serve the Erlang backend bridge until interrupted (the release's
    long-running node role; BEAM side: bridge/erlang/lasp_tpu_backend.erl
    with LASP_TPU_BRIDGE_HOST/PORT pointing here)."""
    import time

    from lasp_tpu.bridge import BridgeServer

    server = BridgeServer(host=args.host, port=args.port,
                          n_actors=args.actors, data_dir=args.data_dir)
    port = server.start()
    print(json.dumps({"listening": f"{args.host}:{port}"}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


def _metrics_workload(n_replicas: int) -> None:
    """The built-in observability smoke workload: a small replicated
    gossip run that exercises every instrumented layer — per-type merges
    (orset / orswot / gcounter client writes), a dataflow edge (map), a
    gossip population run to quiescence, and a loopback bridge exchange —
    so a bare ``lasp_tpu metrics`` emits a representative snapshot
    without needing a live system to scrape."""
    from lasp_tpu.bridge import BridgeClient, BridgeServer
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store
    from lasp_tpu.telemetry import span

    with span("cli.metrics_workload", replicas=n_replicas):
        store = Store(n_actors=8)
        ads = store.declare(type="lasp_orset", n_elems=16)
        hits = store.declare(type="riak_dt_gcounter")
        tags = store.declare(type="riak_dt_orswot", n_elems=16)
        graph = Graph(store)
        graph.map(ads, lambda x: ("seen", x))
        rt = ReplicatedRuntime(
            store, graph, n_replicas, ring(n_replicas, min(2, n_replicas - 1))
        )
        for r in range(n_replicas):
            rt.update_at(r % n_replicas, ads, ("add", f"ad{r}"), f"w{r}")
            rt.update_at(r % n_replicas, hits, ("increment",), f"w{r}")
            rt.update_at(r % n_replicas, tags, ("add", f"t{r}"), f"w{r}")
        rt.run_to_convergence(max_rounds=64)
        # loopback bridge exchange: verbs land in the same process
        # registry the snapshot reads
        from lasp_tpu.bridge.etf import Atom

        with BridgeServer(port=0) as server:
            with BridgeClient("127.0.0.1", server.port) as c:
                c.start("metrics_demo")
                c.declare(b"v", "lasp_gset", n_elems=8)
                c.update(b"v", (Atom("add"), b"x"), b"w")
                c.read(b"v")
                c.metrics()


def cmd_metrics(args) -> int:
    """Telemetry snapshot console (the riak-admin status role for the
    metrics subsystem): Prometheus text to stdout, optional JSONL event
    dump, optional watch loop, optional live-bridge scrape."""
    import time

    from lasp_tpu import telemetry

    def emit() -> None:
        if args.bridge:
            from lasp_tpu.bridge import BridgeClient

            host, _, port = args.bridge.rpartition(":")
            with BridgeClient(host or "127.0.0.1", int(port)) as c:
                resp = c.metrics()
            if not (isinstance(resp, tuple) and len(resp) == 2):
                raise RuntimeError(f"bridge metrics verb failed: {resp!r}")
            sys.stdout.write(
                resp[1].decode() if isinstance(resp[1], bytes) else str(resp[1])
            )
        else:
            sys.stdout.write(telemetry.render_prometheus())
        if args.jsonl:
            telemetry.dump_jsonl(sys.stdout)
        sys.stdout.flush()

    if not args.bridge:
        if args.replicas < 2:
            print(
                f"error: --replicas must be >= 2 (a {args.replicas}-replica "
                "population has no gossip edges to observe)",
                file=sys.stderr,
            )
            return 2
        _metrics_workload(args.replicas)
    if args.watch:
        try:
            while True:
                emit()
                print(f"--- watch: next snapshot in {args.watch}s ---")
                time.sleep(args.watch)
                if not args.bridge:
                    _metrics_workload(args.replicas)
        except KeyboardInterrupt:
            return 0
    emit()
    return 0


def _observatory_runtime(n_replicas: int):
    """The live mesh behind ``top``/``trace`` when no --bridge is given:
    an OR-Set + G-Counter population with a combinator edge (``ads`` ->
    map -> ``seen_ads``), seeded at scattered replicas but NOT yet
    converged — so the observatory has real divergence to watch drain.
    Returns the runtime (its store/graph ride on the instance)."""
    from lasp_tpu.dataflow import Graph
    from lasp_tpu.mesh import ReplicatedRuntime, ring
    from lasp_tpu.store import Store

    n = n_replicas
    store = Store(n_actors=max(16, n))
    ads = store.declare(id="ads", type="lasp_orset", n_elems=32)
    hits = store.declare(id="hits", type="riak_dt_gcounter")
    graph = Graph(store)
    graph.map(ads, lambda x: ("seen", x), dst="seen_ads")
    rt = ReplicatedRuntime(store, graph, n, ring(n, min(2, n - 1)))
    rt.update_batch(
        ads,
        [(r, ("add", f"ad{r}"), f"w{r}") for r in range(0, n, max(1, n // 8))],
    )
    rt.update_batch(
        hits,
        [(r, ("increment",), f"w{r}") for r in range(0, n, max(1, n // 4))],
    )
    return rt


def _render_top(health: dict, shard_lag_label: str = "shard lag") -> str:
    """One refresh frame of the ``top`` view as text (pure function of a
    health snapshot, so the CLI test can pin the rendering)."""
    lines = []
    eta = health.get("quiescence_eta")
    lines.append(
        f"convergence: round={health.get('round', 0)} "
        f"replicas={health.get('n_replicas', 0)} "
        f"residual={health.get('residual_total')} "
        f"eta={'?' if eta is None else eta}"
    )
    probe = health.get("probe") or {}
    lag_by_var = probe.get("lag_by_var", {})
    lines.append(f"{'VAR':<20} {'RESIDUAL':>8} {'STALE':>6} {'LAG':>6}")
    residual_by_var = health.get("residual_by_var", {})
    staleness = health.get("staleness", {})
    for v in sorted(residual_by_var, key=lambda x: -residual_by_var[x]):
        lines.append(
            f"{str(v):<20} {residual_by_var[v]:>8} "
            f"{staleness.get(v, 0):>6} {lag_by_var.get(v, '-'):>6}"
        )
    if probe.get("shard_lag"):
        lines.append(
            f"{shard_lag_label}: "
            + "  ".join(
                f"s{i}={sl}" for i, sl in enumerate(probe["shard_lag"])
            )
        )
        lines.append(
            f"worst replica: {probe.get('worst_replica')} "
            f"(lag {probe.get('worst_replica_lag')})"
        )
    alerts = health.get("alerts", [])
    for a in alerts:
        lines.append(f"ALERT: {a}")
    if not alerts:
        lines.append("alerts: none")
    return "\n".join(lines)


def cmd_top(args) -> int:
    """Live cluster-health view (the riak-admin ``top`` role): per-var
    residual/staleness/lag table, per-shard lag, alert lines — refreshed
    from a live bridge's ``{health}`` verb or from a built-in observed
    mesh stepping toward quiescence."""
    import time

    from lasp_tpu.telemetry import get_monitor

    rt = None
    if not args.bridge:
        if args.replicas < 2:
            print(
                "error: --replicas must be >= 2 (nothing to observe)",
                file=sys.stderr,
            )
            return 2
        rt = _observatory_runtime(args.replicas)
    iterations = args.iterations
    i = 0
    try:
        while True:
            if args.bridge:
                from lasp_tpu.bridge import BridgeClient

                host, _, port = args.bridge.rpartition(":")
                with BridgeClient(host or "127.0.0.1", int(port)) as c:
                    resp = c.health()
                if not (isinstance(resp, tuple) and len(resp) == 2):
                    raise RuntimeError(f"bridge health verb failed: {resp!r}")
                health = json.loads(
                    resp[1].decode()
                    if isinstance(resp[1], bytes)
                    else str(resp[1])
                )
            else:
                rt.step()  # one observed gossip round per refresh
                mon = get_monitor()
                mon.probe(rt, n_shards=args.shards)
                health = mon.health()
            print(_render_top(health))
            print("---", flush=True)
            i += 1
            if iterations and i >= iterations:
                return 0
            time.sleep(args.refresh)
    except KeyboardInterrupt:
        return 0


def cmd_trace(args) -> int:
    """Causal-history export: drive the observed mesh (variable ``ads``
    through the ``seen_ads`` map edge), collect the event-log records
    relevant to ``--var`` — its own binds/updates/deliveries plus, via
    the dataflow lineage, its upstream sources' — and write a
    Perfetto/Chrome-trace JSON (open in ui.perfetto.dev or
    chrome://tracing)."""
    from lasp_tpu.telemetry import events as tel_events
    from lasp_tpu.telemetry import get_monitor

    if args.deep:
        tel_events.set_deep(True)
    rt = _observatory_runtime(args.replicas)
    if args.var not in rt.store.ids():
        # validate BEFORE the convergence run: a typo'd --var must not
        # cost the whole workload
        print(
            f"error: unknown variable {args.var!r} "
            f"(workload vars: {sorted(map(str, rt.store.ids()))})",
            file=sys.stderr,
        )
        return 2
    rt.run_to_convergence(max_rounds=args.max_rounds, block=args.block)
    rt.graph.propagate()  # fold the combinator edges' provenance in
    get_monitor().probe(rt)
    lineage = rt.graph.lineage(args.var)
    history = tel_events.causal_history(args.var, lineage)
    with open(args.export, "w") as fp:
        n = tel_events.export_chrome_trace(fp, event_records=history)
    print(json.dumps({
        "var": args.var,
        "events": len(history),
        "trace_events": n,
        "lineage": {
            v: entry["srcs"] for v, entry in lineage.items()
        },
        "export": args.export,
    }))
    return 0


def cmd_flight(args) -> int:
    """Flight-recorder console: drive the observed mesh's fully fused
    convergence (``converge_on_device`` — zero per-round host syncs),
    then print the windows the on-device ring retained: per-round
    per-variable residual records, exactly what the fused dispatch did
    round by round. ``--export`` writes the full snapshot JSON
    (windows + drop counters) for offline diffing."""
    from lasp_tpu.telemetry import device as tel_flight

    if args.replicas < 2:
        print("error: --replicas must be >= 2 (nothing to record)",
              file=sys.stderr)
        return 2
    rt = _observatory_runtime(args.replicas)
    rounds = rt.converge_on_device(max_rounds=args.max_rounds)
    rt.graph.propagate()  # the dataflow megakernel's window too
    ws = tel_flight.windows()
    print(tel_flight.render(ws))
    print(f"converged in {rounds} rounds; "
          f"{len(ws)} flight windows retained")
    if args.export:
        with open(args.export, "w") as fp:
            json.dump(tel_flight.snapshot(), fp, indent=2)
        print(f"exported -> {args.export}")
    return 0


def cmd_roofline(args) -> int:
    """Per-kernel roofline table (the cost-ledger console): achieved
    GB/s and roofline fraction per kernel signature against the device
    capability registry, from a built-in mixed-codec workload (or
    whatever the process already ran when imported in-process).
    ``--export`` writes the full JSON; ``--profile DIR`` additionally
    wraps the workload in a jax.profiler trace (Perfetto-openable)."""
    from lasp_tpu.bench_scenarios import roofline_workload
    from lasp_tpu.telemetry import device_capability, get_ledger
    from lasp_tpu.telemetry.roofline import profile_capture

    if args.replicas < 2:
        print("error: --replicas must be >= 2 (no gossip edges)",
              file=sys.stderr)
        return 2
    if args.profile:
        with profile_capture(args.profile):
            roofline_workload(args.replicas, rounds=args.rounds)
    else:
        roofline_workload(args.replicas, rounds=args.rounds)
    cap = device_capability()
    ledger = get_ledger()
    snap = ledger.snapshot()
    peak = cap["peak_GBps"]
    print(
        f"device: {cap['platform']}/{cap['device_kind']}  "
        f"roofline {peak if peak is not None else '?'} GB/s "
        f"({cap['source']})"
    )
    print(f"{'KERNEL':<42} {'DISP':>5} {'ROUNDS':>6} {'MB':>9} "
          f"{'ms':>9} {'GB/s':>8} {'ROOF%':>7}")
    for ent in snap:
        gbps = ent["achieved_GBps"]
        frac = ent["roofline_frac"]
        print(
            f"{ent['kernel']:<42} {ent['dispatches']:>5} "
            f"{ent['rounds']:>6} {ent['bytes'] / 1e6:>9.3f} "
            f"{ent['seconds'] * 1e3:>9.2f} "
            f"{gbps if gbps is not None else '-':>8} "
            f"{('%.2f%%' % (100 * frac)) if frac is not None else '-':>7}"
        )
    summary = ledger.summary()
    print(
        f"total: {summary['totals']['dispatches']} dispatches, "
        f"{summary['totals']['bytes'] / 1e6:.3f} MB, "
        f"achieved {summary['achieved_GBps']} GB/s, "
        f"roofline_frac {summary['roofline_frac']}"
    )
    if args.export:
        with open(args.export, "w") as f:
            json.dump(
                {"capability": cap, "kernels": snap, "summary": summary},
                f, indent=2,
            )
        print(f"exported: {args.export}")
    if args.profile:
        print(f"profile trace: {args.profile} (open in Perfetto / "
              "TensorBoard)")
    return 0


def cmd_inspect(args) -> int:
    from lasp_tpu.store import HostStore
    from lasp_tpu.store.checkpoint import loads_manifest

    with HostStore(args.path) as hs:
        manifest = hs.get("manifest")
        out = {"stats": hs.stats(), "keys": hs.keys()}
        if manifest is not None:
            # restricted unpickler: inspect runs on ARBITRARY paths and a
            # stock pickle.loads would execute attacker-controlled code
            m = loads_manifest(manifest)
            out["kind"] = m.get("kind")
            if "vars" in m:  # runtime snapshots: inline entries
                out["vars"] = {
                    str(vid): entry["type_name"]
                    for vid, entry in m["vars"].items()
                }
            else:  # store logs: header + per-var varmeta records
                from lasp_tpu.store.checkpoint import _varmeta_key

                out["vars"] = {}
                for vid in m.get("var_ids", []):
                    raw = hs.get(_varmeta_key(vid))
                    entry = loads_manifest(raw) if raw is not None else None
                    out["vars"][str(vid)] = (
                        entry["type_name"] if entry else "<missing varmeta>"
                    )
            if "n_replicas" in m:
                out["n_replicas"] = m["n_replicas"]
        print(json.dumps(out, indent=2, default=str))
    return 0


def main(argv=None) -> int:
    import os

    if os.environ.get("JAX_PLATFORMS"):
        # honor the documented JAX_PLATFORMS contract even where a
        # sitecustomize has re-pinned jax_platforms at interpreter startup
        # (a no-op on stock environments: the config default already
        # mirrors the env var)
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    p = argparse.ArgumentParser(prog="lasp_tpu", description=__doc__)
    sub = p.add_subparsers(dest="verb", required=True)

    sub.add_parser("status", help="devices + version (ringready analogue)")

    from lasp_tpu.config import get_config

    cfg = get_config()
    sim = sub.add_parser("simulate", help="run a gossip population to fixpoint")
    sim.add_argument("--replicas", type=int, default=1024)
    sim.add_argument("--topology", choices=["ring", "random", "scale_free"],
                     default="random")
    sim.add_argument("--fanout", type=int, default=cfg.fanout)
    sim.add_argument(
        "--type",
        default="lasp_orset",
        # set family writes ("add", item); the G-Counter writes
        # ("increment",) per writer lane — other types (ivar/map) have no
        # meaningful one-op simulate shape and stay excluded
        choices=["lasp_gset", "lasp_orset", "lasp_orset_gbtree",
                 "riak_dt_gcounter", "riak_dt_orswot"],
    )
    sim.add_argument("--elems", type=int, default=64)
    sim.add_argument("--writers", type=int, default=8)
    sim.add_argument("--max-rounds", type=int, default=256)

    bench = sub.add_parser("bench", help="run the headline benchmark")
    bench.add_argument("--replicas", type=int, default=0)

    ch = sub.add_parser(
        "chaos",
        help="fault-injection soak: run a population through a nemesis "
             "preset and verify the convergence-under-failure "
             "invariants (docs/RESILIENCE.md)",
    )
    # literal list (not chaos.PRESETS): importing the chaos package here
    # would pull jax into every CLI start; tests/chaos/test_engine.py
    # pins this against the registry
    ch.add_argument("--preset", required=True,
                    choices=["ring-cut", "rolling-crash", "flaky-links",
                             "slow-shard", "delay-links"])
    ch.add_argument("--replicas", type=int, default=64)
    ch.add_argument("--topology", choices=["ring", "random", "scale_free"],
                    default="ring")
    ch.add_argument("--fanout", type=int, default=cfg.fanout)
    ch.add_argument("--type", default="lasp_gset",
                    choices=["lasp_gset", "lasp_orset", "riak_dt_orswot"])
    ch.add_argument("--elems", type=int, default=64)
    ch.add_argument("--writers", type=int, default=8)
    ch.add_argument("--seed", type=int, default=0)
    ch.add_argument("--rounds", type=int, default=12,
                    help="fault-window length in gossip rounds")
    ch.add_argument("--max-rounds", type=int, default=4096,
                    help="soak budget (rounds) before giving up")
    ch.add_argument("--mode", choices=["dense", "frontier"],
                    default="dense")
    ch.add_argument("--no-replay", action="store_true",
                    help="skip the replay-determinism second run")

    qu = sub.add_parser(
        "quorum",
        help="quorum coordination soak: batched get/put FSMs under a "
             "nemesis preset + the no-acked-write-lost invariant "
             "(docs/RESILIENCE.md)",
    )
    qu.add_argument("--preset", required=True,
                    choices=["ring-cut", "rolling-crash", "flaky-links",
                             "slow-shard", "delay-links"])
    qu.add_argument("--replicas", type=int, default=32)
    qu.add_argument("--topology", choices=["ring", "random", "scale_free"],
                    default="ring")
    qu.add_argument("--fanout", type=int, default=cfg.fanout)
    qu.add_argument("--writes", type=int, default=12,
                    help="quorum puts issued across the fault window")
    qu.add_argument("--reads", type=int, default=8,
                    help="degraded quorum gets issued alongside")
    qu.add_argument("--n", type=int, default=3, help="preflist width N")
    qu.add_argument("--r", type=int, default=2, help="read quorum R")
    qu.add_argument("--w", type=int, default=2, help="write quorum W")
    qu.add_argument("--timeout", type=int, default=4,
                    help="per-attempt wait in rounds")
    qu.add_argument("--retries", type=int, default=3,
                    help="coordinator re-picks before a partial-quorum "
                         "failure")
    qu.add_argument("--seed", type=int, default=0)
    qu.add_argument("--rounds", type=int, default=10,
                    help="fault-window length in gossip rounds")
    qu.add_argument("--engine", choices=["batched", "sequential"],
                    default="batched")
    qu.add_argument("--no-replay", action="store_true",
                    help="skip the replay-determinism second run")
    qu.add_argument("--hints", default=None, metavar="PATH",
                    help="durable hint-log path (default: in-memory)")
    qu.add_argument("--prune-hints", action="store_true",
                    help="after the harness converges fault-free, "
                         "reclaim every remaining hint record (safe: "
                         "the population has verifiably absorbed them) "
                         "and report the count")

    aae = sub.add_parser(
        "aae",
        help="active anti-entropy scrub: inject silent corruption "
             "(bit-rot / corrupt-partition presets), detect it via the "
             "Merkle hash forest, localize, quorum-repair, and verify "
             "the healed population bit-equal to a fault-free twin "
             "(docs/RESILIENCE.md 'Active anti-entropy')",
    )
    # literal list (the no-jax-at-parse rule, like --preset above);
    # tests/chaos/test_engine.py pins it against CORRUPTION_PRESETS
    aae.add_argument("--preset", default="bit-rot",
                     choices=["bit-rot", "corrupt-partition"])
    aae.add_argument("--replicas", type=int, default=32)
    aae.add_argument("--topology", choices=["ring", "random",
                                            "scale_free"],
                     default="ring")
    aae.add_argument("--fanout", type=int, default=cfg.fanout)
    aae.add_argument("--type", default="lasp_gset",
                     choices=["lasp_gset", "lasp_orset",
                              "riak_dt_orswot"])
    aae.add_argument("--elems", type=int, default=64)
    aae.add_argument("--writers", type=int, default=8)
    aae.add_argument("--seed", type=int, default=0)
    aae.add_argument("--rounds", type=int, default=8,
                     help="corruption-window length in gossip rounds")
    aae.add_argument("--scrub-every", type=int, default=1,
                     help="verify/exchange cadence in rounds (bounds "
                          "detection latency; cadences > 1 require "
                          "--mode frontier — dense all-dirty marks "
                          "launder corruption between scrubs)")
    aae.add_argument("--mode", choices=["dense", "frontier"],
                     default="dense")
    aae.add_argument("--seg-size", type=int, default=8,
                     help="Merkle tree leaves per segment")
    aae.add_argument("--max-rounds", type=int, default=512)
    aae.add_argument("--no-replay", action="store_true",
                     help="skip the replay-determinism second run")

    sv = sub.add_parser(
        "serve",
        help="serving-front-end soak: open-loop simulated clients "
             "(Zipf keys, write+read+watch mix) through the coalescing "
             "ingest + vectorized threshold fan-out, with admission "
             "control, a composite nemesis, and the no-acked-write-lost "
             "check (docs/SERVING.md)",
    )
    sv.add_argument("--replicas", type=int, default=32)
    sv.add_argument("--clients", type=int, default=2000,
                    help="simulated client fleet size")
    sv.add_argument("--ticks", type=int, default=24,
                    help="run length in serving cycles")
    sv.add_argument("--arrivals", type=int, default=400,
                    help="open-loop request arrivals per tick")
    sv.add_argument("--burst", type=int, default=5,
                    help="mid-run overload multiplier (1 = no burst)")
    sv.add_argument("--watches", type=int, default=1000,
                    help="standing threshold watches registered up front")
    sv.add_argument("--parity", type=int, default=4096,
                    help="post-run vectorized-vs-per-watch threshold "
                         "parity size (0 = skip)")
    sv.add_argument("--no-chaos", action="store_true",
                    help="skip the composite nemesis")
    sv.add_argument("--seed", type=int, default=7)

    scen = sub.add_parser("scenario", help="run a BASELINE eval config")
    # literal list (not the SCENARIOS registry): importing bench_scenarios
    # here would pull jax into every CLI invocation including --help;
    # tests/ops/test_scenarios.py::test_cli_scenario_choices_in_sync pins
    # this against the registry
    scen.add_argument(
        "name",
        choices=["aae_scrub", "adcounter_10m", "adcounter_6",
                 "bridge_throughput",
                 "chaos_heal", "dataflow_chain", "elastic_rebalance",
                 "frontier_sparse",
                 "gset_1k", "ingest_storm", "many_vars", "mesh_scale",
                 "orset_100k",
                 "packed_vs_dense",
                 "partitioned_gossip", "pipeline_1m", "quorum_kv",
                 "serve_load"],
    )
    scen.add_argument("--replicas", type=int, default=0,
                      help="override the population for sized scenarios")

    met = sub.add_parser(
        "metrics",
        help="telemetry snapshot: Prometheus text (+ JSONL events); "
             "runs a 2-replica gossip workload unless --bridge scrapes "
             "a live server",
    )
    met.add_argument("--replicas", type=int, default=2,
                     help="population of the built-in workload")
    met.add_argument("--jsonl", action="store_true",
                     help="also dump span + metric events as JSONL")
    met.add_argument("--watch", type=float, default=0,
                     metavar="SECONDS",
                     help="re-emit every SECONDS until interrupted")
    met.add_argument("--bridge", default=None, metavar="HOST:PORT",
                     help="scrape a live bridge's {metrics} verb instead "
                          "of running the built-in workload")

    top = sub.add_parser(
        "top",
        help="live cluster-health view: per-var residual/staleness/lag "
             "table + shard lag + alerts, refreshed against a running "
             "mesh (or --bridge scraping a live {health} verb)",
    )
    top.add_argument("--replicas", type=int, default=64,
                     help="population of the built-in observed mesh")
    top.add_argument("--refresh", type=float, default=1.0,
                     metavar="SECONDS", help="delay between frames")
    top.add_argument("--iterations", type=int, default=0,
                     help="stop after N frames (0 = until interrupted)")
    top.add_argument("--shards", type=int, default=None,
                     help="shard count for the lag aggregation "
                          "(default: the runtime's partition plan, else 1)")
    top.add_argument("--bridge", default=None, metavar="HOST:PORT",
                     help="scrape a live bridge's {health} verb instead "
                          "of running the built-in mesh")

    tr = sub.add_parser(
        "trace",
        help="export a variable's causal event history (its own events "
             "plus upstream combinator sources) as Perfetto/Chrome-trace "
             "JSON",
    )
    tr.add_argument("--var", required=True,
                    help="variable to trace (workload vars: ads, "
                         "seen_ads, hits)")
    tr.add_argument("--export", required=True, metavar="FILE",
                    help="output path for the Chrome-trace JSON")
    tr.add_argument("--replicas", type=int, default=64)
    tr.add_argument("--max-rounds", type=int, default=256)
    tr.add_argument("--deep", action="store_true",
                    help="turn on deep tracing (per-op / per-merge / "
                         "per-edge events) for the driven workload")
    tr.add_argument("--block", type=int, default=1,
                    help="fused-window size for the driven convergence "
                         "(>1 runs device-resident blocks; the flight "
                         "recorder keeps the per-round records real)")

    fl = sub.add_parser(
        "flight",
        help="drive a fused convergence and dump the on-device flight "
             "recorder: per-round residual records retained by the "
             "in-loop ring (docs/OBSERVABILITY.md)",
    )
    fl.add_argument("--replicas", type=int, default=64)
    fl.add_argument("--max-rounds", type=int, default=256)
    fl.add_argument("--export", default=None, metavar="FILE",
                    help="write the full flight snapshot as JSON")

    roof = sub.add_parser(
        "roofline",
        help="per-kernel cost-ledger table: achieved GB/s + roofline "
             "fraction per kernel signature against the device "
             "capability registry (docs/OBSERVABILITY.md)",
    )
    roof.add_argument("--replicas", type=int, default=256,
                      help="population of the built-in workload")
    roof.add_argument("--rounds", type=int, default=3,
                      help="re-dirty/convergence cycles to drive")
    roof.add_argument("--export", default=None, metavar="FILE",
                      help="write capability + per-kernel table as JSON")
    roof.add_argument("--profile", default=None, metavar="DIR",
                      help="wrap the workload in a jax.profiler trace "
                           "(Perfetto-openable) written to DIR")

    ins = sub.add_parser("inspect", help="list a checkpoint's contents")
    ins.add_argument("path")

    br = sub.add_parser("bridge", help="serve the Erlang backend bridge")
    br.add_argument("--host", default="127.0.0.1")
    br.add_argument("--port", type=int, default=9190)
    br.add_argument("--actors", type=int, default=cfg.n_actors)
    br.add_argument("--data-dir", default=None,
                    help="durable per-name stores (eleveldb role); "
                         "omit for in-memory")

    args = p.parse_args(argv)
    return {
        "status": cmd_status,
        "simulate": cmd_simulate,
        "bench": cmd_bench,
        "chaos": cmd_chaos,
        "quorum": cmd_quorum,
        "aae": cmd_aae,
        "serve": cmd_serve,
        "scenario": cmd_scenario,
        "metrics": cmd_metrics,
        "top": cmd_top,
        "trace": cmd_trace,
        "flight": cmd_flight,
        "roofline": cmd_roofline,
        "inspect": cmd_inspect,
        "bridge": cmd_bridge,
    }[args.verb](args)


if __name__ == "__main__":
    sys.exit(main())
