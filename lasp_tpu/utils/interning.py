"""Host-side interning of arbitrary hashable terms to dense tensor indices.

The reference identifies set elements by arbitrary Erlang terms and actors /
variable ids by crypto UUIDs (druuid, ``src/lasp.erl:159``) — unbounded,
random identity. Dense tensor encodings need small integer indices with
*deterministic* allocation, so each variable owns an ``Interner`` mapping
payload terms to slots in its element universe, and the store owns one for
actors. This (plus counter-based OR-set tokens) replaces the crypto/druuid
native dependencies identified in SURVEY.md §2.4.
"""

from __future__ import annotations


class Interner:
    """Bidirectional term <-> dense index map with a fixed capacity."""

    def __init__(self, capacity: int, kind: str = "term"):
        self.capacity = capacity
        self.kind = kind
        self._to_idx: dict = {}
        self._from_idx: list = []

    def __len__(self) -> int:
        return len(self._from_idx)

    def __contains__(self, term) -> bool:
        return term in self._to_idx

    def intern(self, term) -> int:
        """Index for ``term``, allocating the next free slot on first use."""
        idx = self._to_idx.get(term)
        if idx is not None:
            return idx
        if len(self._from_idx) >= self.capacity:
            raise CapacityError(
                f"{self.kind} universe full ({self.capacity}); "
                f"cannot intern {term!r} — declare the variable with a larger "
                f"capacity"
            )
        idx = len(self._from_idx)
        self._to_idx[term] = idx
        self._from_idx.append(term)
        return idx

    def index_of(self, term) -> int:
        """Index for an already-interned term; KeyError if unknown."""
        return self._to_idx[term]

    def term_of(self, idx: int):
        return self._from_idx[idx]

    def terms(self) -> list:
        return list(self._from_idx)

    def decode_mask(self, mask) -> frozenset:
        """Boolean membership mask -> set of interned terms."""
        return frozenset(
            self._from_idx[i] for i, hit in enumerate(mask) if hit and i < len(self)
        )


class CapacityError(RuntimeError):
    """A fixed-shape universe (elements/actors/tokens) ran out of slots."""
