"""Utilities: interning, config, metrics."""

from .interning import CapacityError, Interner

__all__ = ["Interner", "CapacityError"]
