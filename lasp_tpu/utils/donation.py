"""Buffer-donation policy, in one place.

Donation lets a jitted step recycle its input buffers for its outputs —
at population scale that is a full store copy of HBM. The CPU backend
does not implement donation (it warns and copies), so the policy is
"donate on accelerators only"; every donation site routes through here
so the rule can change in exactly one place.
"""

from __future__ import annotations

import jax


def donate_argnums(*nums: int) -> tuple:
    """``nums`` on accelerators, ``()`` on CPU (where donation would only
    warn). Pass the result to ``jax.jit(..., donate_argnums=...)``."""
    return nums if jax.devices()[0].platform != "cpu" else ()
