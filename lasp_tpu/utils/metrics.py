"""Step-level metrics and tracing (SURVEY.md §5 auxiliary subsystems).

The reference has only lager log lines plus per-type ``stats/1``
introspection (``src/lasp_orset.erl:156-192``); riak_core's stat subsystem
is not wired. The TPU build makes observability first-class through
``lasp_tpu.telemetry`` (typed registry + spans + Prometheus/JSONL export);
this module keeps the original surfaces alive:

- :class:`StepTrace` — the per-runtime round record, now a thin
  compatibility facade over the telemetry registry: every
  ``record_round`` still appends to the local round list (``summary()``,
  ``bench.py`` and the CLI read it unchanged) and ALSO forwards a
  dispatch count + timing into the process-global registry
  (``step_dispatches_total`` / ``step_dispatch_seconds``), so runtime
  activity shows up in a Prometheus scrape without touching callers.
- :func:`profile` — the ``jax.profiler`` block tracer (re-exported by
  ``lasp_tpu.telemetry`` as the canonical home).
"""

from __future__ import annotations

import contextlib
import time


class StepTrace:
    """Append-only record of bulk-synchronous rounds: residuals, timings,
    and arbitrary counters. One per runtime/graph; cheap enough to always
    keep on. Compatibility facade: the local record is authoritative for
    ``summary()``; each ``record_round`` also mirrors into the telemetry
    registry (one *dispatch* per call — fused blocks count their rounds
    separately via the runtime's ``gossip_rounds_total``)."""

    def __init__(self):
        self.rounds: list[dict] = []
        self.counters: dict[str, int] = {}
        self._tel: "tuple | None" = None  # (generation, counter, histogram)

    def record_round(self, residual: int, seconds: float, **extra) -> None:
        self.rounds.append({"residual": residual, "seconds": seconds, **extra})
        # lazy import: utils.metrics must stay importable before the
        # telemetry package (which re-exports profile from here) finishes
        # initializing
        from ..telemetry import registry as _reg

        if not _reg.enabled():
            return
        # instruments cached per registry generation: this runs per step
        # dispatch, and a name+label lookup each time is measurable
        # against small steps (the overhead guard's workload)
        gen = _reg.generation()
        if self._tel is None or self._tel[0] != gen:
            reg = _reg.get_registry()
            self._tel = (
                gen,
                reg.counter(
                    "step_dispatches_total",
                    help="compiled step/block dispatches issued by runtimes",
                ),
                reg.histogram(
                    "step_dispatch_seconds",
                    help="wall time per compiled step/block dispatch",
                ),
            )
        self._tel[1].inc()
        self._tel[2].observe(seconds)

    def bump(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    @property
    def total_rounds(self) -> int:
        return len(self.rounds)

    @property
    def total_seconds(self) -> float:
        return sum(r["seconds"] for r in self.rounds)

    def summary(self) -> dict:
        residuals = [r["residual"] for r in self.rounds]
        return {
            "rounds": len(self.rounds),
            "seconds": round(self.total_seconds, 6),
            "residual_path": residuals,
            **self.counters,
        }


@contextlib.contextmanager
def profile(log_dir: str):
    """``jax.profiler`` trace around a block (view with TensorBoard/xprof).

    Exception-safe on both edges: a ``start_trace`` failure propagates
    without attempting ``stop_trace`` (stopping a never-started trace
    raises its own error, MASKING the original one), and a ``stop_trace``
    failure while the body is already raising is suppressed so the body's
    error — the one the user needs — survives."""
    import jax

    jax.profiler.start_trace(log_dir)  # a failure here has nothing to stop
    try:
        yield
    except BaseException:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass  # the body's exception is the one that must propagate
        raise
    else:
        jax.profiler.stop_trace()


class Timer:
    __slots__ = ("t0", "elapsed")

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.t0
