"""Step-level metrics and tracing (SURVEY.md §5 auxiliary subsystems).

The reference has only lager log lines plus per-type ``stats/1``
introspection (``src/lasp_orset.erl:156-192``); riak_core's stat subsystem
is not wired. The TPU build makes observability first-class: every
convergence loop records per-round residuals and wall time, CRDT ``stats``
are cheap tensor reductions, and ``profile()`` wraps a block in a
``jax.profiler`` trace for XLA-level inspection."""

from __future__ import annotations

import contextlib
import time


class StepTrace:
    """Append-only record of bulk-synchronous rounds: residuals, timings,
    and arbitrary counters. One per runtime/graph; cheap enough to always
    keep on."""

    def __init__(self):
        self.rounds: list[dict] = []
        self.counters: dict[str, int] = {}

    def record_round(self, residual: int, seconds: float, **extra) -> None:
        self.rounds.append({"residual": residual, "seconds": seconds, **extra})

    def bump(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by

    @property
    def total_rounds(self) -> int:
        return len(self.rounds)

    @property
    def total_seconds(self) -> float:
        return sum(r["seconds"] for r in self.rounds)

    def summary(self) -> dict:
        residuals = [r["residual"] for r in self.rounds]
        return {
            "rounds": len(self.rounds),
            "seconds": round(self.total_seconds, 6),
            "residual_path": residuals,
            **self.counters,
        }


@contextlib.contextmanager
def profile(log_dir: str):
    """``jax.profiler`` trace around a block (view with TensorBoard/xprof)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Timer:
    __slots__ = ("t0", "elapsed")

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self.t0
