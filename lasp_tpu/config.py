"""Unified runtime configuration (SURVEY.md §5 "Config / flag system").

The reference scatters configuration across compile-time macros
(``include/lasp.hrl:8-43``: backend selection, N/R/W quorums, timeouts),
cuttlefish schemas (``priv/lasp.schema:4-8``), and templated app/vm args
(``rel/files/app.config``, ``rel/vars.config``). The TPU build replaces
all three with ONE typed, frozen dataclass: defaults in code, overrides
from ``LASP_*`` environment variables (the release-template role), and
explicit construction for programmatic use.

Every field maps to the env var ``LASP_<FIELDNAME upper>``; unknown
``LASP_*`` variables are rejected loudly (a typo'd knob must not be
silently ignored — the same policy as the store's ``ALLOWED_CAPS``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping, Optional


@dataclasses.dataclass(frozen=True)
class LaspConfig:
    # -- capacity defaults (the include/lasp.hrl compile-time macro role) --
    #: default per-variable writer universe (Store(n_actors=...));
    #: element/token capacities stay per-declare arguments on purpose —
    #: they size each variable's universe, not the process
    n_actors: int = 16

    # -- gossip / engine ----------------------------------------------------
    #: pull-gossip fan-in for cli simulate / scenario topologies
    fanout: int = 3
    #: rounds per fused dispatch for the engine-scale scenarios and cli
    fused_block: int = 4
    #: headline gossip kernel: auto | xla | pallas
    gossip_impl: str = "auto"

    # -- benchmark knobs (bench.py / cli bench) ------------------------------
    bench_replicas: Optional[int] = None  # None = bench picks per platform
    bench_northstar_replicas: Optional[int] = None
    bench_block: int = 4

    # -- mesh ---------------------------------------------------------------
    #: extent of the tensor-parallel "state" axis in build_mesh
    mesh_state_axis: int = 1

    # -- telemetry ----------------------------------------------------------
    #: flight-recorder ring depth K: the last K rounds of per-round
    #: records each fused window retains on device and drains on its
    #: sync (telemetry/device.py; windows longer than K keep the
    #: suffix and count the lost prefix as overwritten)
    flight_rounds: int = 64

    # -- bridge -------------------------------------------------------------
    #: wire codec selection: auto (native .so when present AND it passes
    #: the byte-conformance self-check, else python) | python (forced)
    etf: str = "auto"

    @classmethod
    def field_env_name(cls, field_name: str) -> str:
        return f"LASP_{field_name.upper()}"

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "LaspConfig":
        """Defaults overridden by ``LASP_*`` env vars. Unknown ``LASP_*``
        names raise (except the driver/runner-owned ``LASP_BENCH_*`` and
        ``LASP_DRYRUN_*`` timeout knobs, which bench.py/__graft_entry__
        own directly)."""
        env = os.environ if env is None else env
        fields = {f.name: f for f in dataclasses.fields(cls)}
        by_env = {cls.field_env_name(n): n for n in fields}
        overrides = {}
        passthrough_prefixes = (
            "LASP_BENCH_PROBE",
            "LASP_BENCH_TPU_TIMEOUT",
            "LASP_BENCH_CPU_TIMEOUT",
            "LASP_BENCH_TOTAL_BUDGET",
            "LASP_BENCH_CHILD_BUDGET",
            "LASP_DRYRUN",
            "LASP_STATEM",  # test-suite soak depth (tests/lattice)
            "LASP_TELEMETRY",  # telemetry sinks (JSONL path etc.),
            # read directly by lasp_tpu.telemetry.spans

            "LASP_WATCH",  # tools/tpu_capture.py watcher knobs
            "LASP_ONESHOT",  # tools/tpu_oneshot.py capture knobs
        )
        for key, raw in env.items():
            if not key.startswith("LASP_"):
                continue
            if any(key.startswith(p) for p in passthrough_prefixes):
                continue
            if key not in by_env:
                known = ", ".join(sorted(by_env))
                raise ValueError(
                    f"unknown config variable {key} (known: {known})"
                )
            name = by_env[key]
            ftype = fields[name].type
            if ftype in ("int", "Optional[int]", int):
                overrides[name] = int(raw)
            else:
                overrides[name] = raw
        return cls(**overrides)

    def validate(self) -> "LaspConfig":
        if self.gossip_impl not in ("auto", "xla", "pallas"):
            raise ValueError(f"gossip_impl: {self.gossip_impl!r}")
        if self.etf not in ("auto", "python"):
            raise ValueError(f"etf: {self.etf!r} (auto | python)")
        for name in ("n_actors", "fanout", "fused_block", "mesh_state_axis",
                     "bench_block", "flight_rounds"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        return self


def get_config() -> LaspConfig:
    """The process-wide config, resolved from the environment once."""
    global _CONFIG
    if _CONFIG is None:
        _CONFIG = LaspConfig.from_env().validate()
    return _CONFIG


def set_config(cfg: LaspConfig) -> LaspConfig:
    """Install ``cfg`` (validated) as the process-wide config and notify
    already-materialized dependents. Today that is the ETF wire codec:
    its implementation choice (``cfg.etf``) is baked at first import of
    ``lasp_tpu.bridge.etf``, so a later config change must re-run the
    selection — without this hook, ``LaspConfig(etf="python")`` would
    silently not take effect."""
    global _CONFIG
    _CONFIG = cfg.validate()
    import sys

    etf_mod = sys.modules.get("lasp_tpu.bridge.etf")
    if etf_mod is not None:
        etf_mod.reselect()
    return _CONFIG


_CONFIG: Optional[LaspConfig] = None
