"""Hot-path kernels (SURVEY.md §7 stance: Pallas/packed kernels for the
irregular merge cores). The packed OR-Set is the HBM-bandwidth-optimal
encoding of the framework's hottest object (reference hot path
``src/lasp_core.erl:300-301``)."""

from .packed import (
    PackedORSet,
    PackedORSetSpec,
    PackedORSetState,
    pack_orset,
    unpack_orset,
)
from .flatpack import FlatORSet, FlatORSetSpec, FlatORSetState
from .fused import fused_gossip_rounds

__all__ = [
    "FlatORSet",
    "FlatORSetSpec",
    "FlatORSetState",
    "PackedORSet",
    "PackedORSetSpec",
    "PackedORSetState",
    "fused_gossip_rounds",
    "pack_orset",
    "unpack_orset",
]
