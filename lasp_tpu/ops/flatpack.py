"""Flat bit-packed OR-Set: the mesh wire format — 1 bit per (elem, token).

``PackedORSet`` (``lasp_tpu.ops.packed``) packs the token axis per element
into whole uint32 words, which wastes up to 31 bits per element when token
spaces are tiny — and *tiny token spaces are the norm for dataflow outputs*
(a product's causal tokens number ``T_l * T_r`` of its inputs, e.g. 2).
This codec flattens the whole (elem, token) grid into one bit axis
(``bit = e * T + t``) and packs that, so a 50-element, 2-token product
state costs 4 words instead of 50 — the densest possible HBM/ICI encoding
of OR-Set state, and the representation ``ReplicatedRuntime(packed=True)``
holds replica populations in.

Semantics are IDENTICAL to the dense codec (``src/lasp_orset.erl:128-134``
merge / :67-73 value): ``pack``/``unpack`` convert losslessly, and all
non-hot operations (value decode, threshold checks, strict inflation)
delegate to the dense codec through ``unpack`` — only the hot kernels
(merge, equal, inflation) run natively on words.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..lattice.orset import ORSet, ORSetSpec, ORSetState


@dataclasses.dataclass(frozen=True)
class FlatORSetSpec:
    dense: ORSetSpec

    @property
    def n_bits(self) -> int:
        return self.dense.n_elems * self.dense.n_tokens

    @property
    def n_words(self) -> int:
        return (self.n_bits + 31) // 32


class FlatORSetState(NamedTuple):
    exists: jax.Array  # uint32[W]
    removed: jax.Array  # uint32[W]


def _pack_bits(spec: FlatORSetSpec, plane: jax.Array) -> jax.Array:
    """bool[..., E, T] -> uint32[..., W]."""
    flat = plane.reshape(plane.shape[:-2] + (spec.n_bits,))
    pad = spec.n_words * 32 - spec.n_bits
    flat = jnp.pad(flat.astype(jnp.uint32), [(0, 0)] * (flat.ndim - 1) + [(0, pad)])
    flat = flat.reshape(flat.shape[:-1] + (spec.n_words, 32))
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(flat * weights, axis=-1, dtype=jnp.uint32)


def _unpack_bits(spec: FlatORSetSpec, words: jax.Array) -> jax.Array:
    """uint32[..., W] -> bool[..., E, T]."""
    bits = (words[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
    flat = bits.reshape(words.shape[:-1] + (spec.n_words * 32,))
    d = spec.dense
    return flat[..., : spec.n_bits].astype(bool).reshape(
        words.shape[:-1] + (d.n_elems, d.n_tokens)
    )


class FlatORSet:
    name = "lasp_orset_flat"
    leafwise_join = "or"

    @staticmethod
    def new(spec: FlatORSetSpec) -> FlatORSetState:
        z = jnp.zeros((spec.n_words,), dtype=jnp.uint32)
        return FlatORSetState(exists=z, removed=z)

    # -- conversions ---------------------------------------------------------
    @staticmethod
    def pack(spec: FlatORSetSpec, dense: ORSetState) -> FlatORSetState:
        return FlatORSetState(
            exists=_pack_bits(spec, dense.exists),
            # canonicalize: tombstone bits only meaningful where minted
            removed=_pack_bits(spec, dense.removed & dense.exists),
        )

    @staticmethod
    def unpack(spec: FlatORSetSpec, state: FlatORSetState) -> ORSetState:
        return ORSetState(
            exists=_unpack_bits(spec, state.exists),
            removed=_unpack_bits(spec, state.removed),
        )

    # -- hot kernels (native on words) ---------------------------------------
    @staticmethod
    def merge(spec, a: FlatORSetState, b: FlatORSetState) -> FlatORSetState:
        return FlatORSetState(exists=a.exists | b.exists, removed=a.removed | b.removed)

    @staticmethod
    def equal(spec, a: FlatORSetState, b: FlatORSetState) -> jax.Array:
        return jnp.all(a.exists == b.exists) & jnp.all(
            (a.removed & a.exists) == (b.removed & b.exists)
        )

    @staticmethod
    def is_inflation(spec, prev, cur) -> jax.Array:
        return jnp.all((prev.exists & ~cur.exists) == 0)

    @staticmethod
    def is_strict_inflation(spec, prev, cur) -> jax.Array:
        return ORSet.is_strict_inflation(
            spec.dense, FlatORSet.unpack(spec, prev), FlatORSet.unpack(spec, cur)
        )

    # -- decode (delegates through unpack) -----------------------------------
    @staticmethod
    def value(spec, state) -> jax.Array:
        return ORSet.value(spec.dense, FlatORSet.unpack(spec, state))

    @staticmethod
    def member_mask(spec, state) -> jax.Array:
        return ORSet.member_mask(spec.dense, FlatORSet.unpack(spec, state))

    @staticmethod
    def threshold_met(spec, state, threshold) -> jax.Array:
        thr = threshold
        if isinstance(getattr(thr, "state", None), FlatORSetState):
            thr = thr._replace(state=FlatORSet.unpack(spec, thr.state))
        return ORSet.threshold_met(spec.dense, FlatORSet.unpack(spec, state), thr)

    @staticmethod
    def stats(spec, state) -> dict:
        return ORSet.stats(spec.dense, FlatORSet.unpack(spec, state))

    # -- vectorized seeding (device-side batched client ops) -----------------
    @staticmethod
    def scatter_tokens(
        spec: FlatORSetSpec, states, rows: jax.Array, elems: jax.Array,
        tokens: jax.Array,
    ):
        """OR token bits into a REPLICATED state ``[R, W]`` at ``(rows[i],
        elems[i], tokens[i])`` — the device-side bulk-add kernel for
        population-scale seeding (one scatter for millions of client adds,
        no host loop). The (row, elem, token) triples MUST be unique: with
        unique bits, scatter-add into a zero buffer is carry-free and equals
        scatter-OR, which XLA has no native combinator for."""
        d = spec.dense
        bit = elems.astype(jnp.uint32) * jnp.uint32(d.n_tokens) + tokens.astype(
            jnp.uint32
        )
        word = (bit // 32).astype(jnp.int32)
        mask = jnp.uint32(1) << (bit % 32)
        add_words = jnp.zeros_like(states.exists).at[rows, word].add(mask)
        return states._replace(
            exists=states.exists | add_words,
            removed=states.removed & ~add_words,
        )
