"""Bit-packed OR-Set: token flags as uint32 words, 8x less HBM than bools.

The dense OR-Set (``lasp_tpu.lattice.orset``) stores ``bool[E, T]`` planes;
XLA materializes bools as one byte each, so a gossip round at 10M replicas
moves ~8x more HBM bytes than the information content. This codec packs the
token axis into ``uint32[E, ceil(T/32)]`` words: merge stays a pure
elementwise OR (now on 32 tokens per lane), value/member become popcount
reductions, and the whole state is 1 bit per token — the encoding the
BASELINE 10M-replica configs run on.

Semantics are IDENTICAL to the dense codec (same reference contract,
``src/lasp_orset.erl:128-134`` merge / :67-73 value); ``pack_orset`` /
``unpack_orset`` convert losslessly, and the property suite cross-checks
every operation against the dense codec.
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..lattice.orset import ORSetSpec, ORSetState


@dataclasses.dataclass(frozen=True)
class PackedORSetSpec:
    n_elems: int
    n_actors: int
    tokens_per_actor: int = 4
    token_space: int | None = None

    @property
    def n_tokens(self) -> int:
        if self.token_space is not None:
            return self.token_space
        return self.n_actors * self.tokens_per_actor

    @property
    def n_words(self) -> int:
        return (self.n_tokens + 31) // 32

    def dense(self) -> ORSetSpec:
        return ORSetSpec(
            n_elems=self.n_elems,
            n_actors=self.n_actors,
            tokens_per_actor=self.tokens_per_actor,
            token_space=self.token_space,
        )


class PackedORSetState(NamedTuple):
    exists: jax.Array  # uint32[E, W]
    removed: jax.Array  # uint32[E, W]


def _word_bit(token_idx):
    return token_idx // 32, jnp.uint32(1) << (token_idx % 32).astype(jnp.uint32)


class PackedORSet:
    name = "lasp_orset_packed"
    leafwise_join = "or"

    @staticmethod
    def new(spec: PackedORSetSpec) -> PackedORSetState:
        shape = (spec.n_elems, spec.n_words)
        return PackedORSetState(
            exists=jnp.zeros(shape, dtype=jnp.uint32),
            removed=jnp.zeros(shape, dtype=jnp.uint32),
        )

    # -- updates ------------------------------------------------------------
    @staticmethod
    def add_by_token(spec, state, elem_idx, token_idx) -> PackedORSetState:
        token_idx = jnp.asarray(token_idx)
        w, bit = _word_bit(token_idx)
        return PackedORSetState(
            exists=state.exists.at[elem_idx, w].set(state.exists[elem_idx, w] | bit),
            removed=state.removed.at[elem_idx, w].set(
                state.removed[elem_idx, w] & ~bit
            ),
        )

    @staticmethod
    def add_exhausted(spec, state, elem_idx, actor_idx) -> jax.Array:
        """Scalar bool: the actor's pool for the element is full (dense
        ``ORSet.add_exhausted`` contract — host op layers raise on this)."""
        k = spec.tokens_per_actor
        offs = actor_idx * k + jnp.arange(k)
        w, bit = _word_bit(offs)
        return jnp.all((state.exists[elem_idx, w] & bit) != 0)

    @staticmethod
    def add(spec, state, elem_idx, actor_idx) -> PackedORSetState:
        """Mint the actor's first free slot (dense ``ORSet.add`` contract:
        pool-exhausted adds are a no-op here; host paths gate on
        ``add_exhausted`` and raise)."""
        k = spec.tokens_per_actor
        base = actor_idx * k
        # extract the actor's k-bit pool spread over words
        offs = base + jnp.arange(k)
        w, bit = _word_bit(offs)
        taken = (state.exists[elem_idx, w] & bit) != 0
        free = jnp.argmax(~taken)
        in_range = ~taken[free]
        slot = base + free
        sw, sbit = _word_bit(slot)
        sbit = jnp.where(in_range, sbit, jnp.uint32(0))
        return PackedORSetState(
            exists=state.exists.at[elem_idx, sw].set(state.exists[elem_idx, sw] | sbit),
            removed=state.removed.at[elem_idx, sw].set(
                state.removed[elem_idx, sw] & ~sbit
            ),
        )

    @staticmethod
    def remove(spec, state, elem_idx) -> PackedORSetState:
        return PackedORSetState(
            exists=state.exists,
            removed=state.removed.at[elem_idx].set(
                state.removed[elem_idx] | state.exists[elem_idx]
            ),
        )

    @staticmethod
    def apply_masks(spec, state, add_tokens, remove_elems) -> PackedORSetState:
        """Batched update kernel (packed counterpart of
        ``ORSet.apply_masks``): ``add_tokens: uint32[E, W]``,
        ``remove_elems: bool[E]``."""
        exists = state.exists | add_tokens
        removed = state.removed | jnp.where(
            remove_elems[..., None], exists, jnp.uint32(0)
        )
        return PackedORSetState(exists=exists, removed=removed)

    # -- lattice ------------------------------------------------------------
    @staticmethod
    def merge(spec, a, b) -> PackedORSetState:
        return PackedORSetState(
            exists=a.exists | b.exists, removed=a.removed | b.removed
        )

    @staticmethod
    def value(spec, state) -> jax.Array:
        """bool[E]: any live token (exists bit without removed bit)."""
        return jnp.any(state.exists & ~state.removed, axis=-1)

    @staticmethod
    def member_mask(spec, state) -> jax.Array:
        return jnp.any(state.exists != 0, axis=-1)

    @staticmethod
    def equal(spec, a, b) -> jax.Array:
        return jnp.all(a.exists == b.exists) & jnp.all(
            (a.removed & a.exists) == (b.removed & b.exists)
        )

    @staticmethod
    def is_inflation(spec, prev, cur) -> jax.Array:
        return jnp.all((prev.exists & ~cur.exists) == 0)

    @staticmethod
    def is_strict_inflation(spec, prev, cur) -> jax.Array:
        inflation = jnp.all((prev.exists & ~cur.exists) == 0)
        changed = jnp.any(
            (prev.exists != cur.exists)
            | ((prev.removed & prev.exists) != (cur.removed & cur.exists))
        )
        return inflation & changed


def pack_orset(spec: PackedORSetSpec, dense: ORSetState) -> PackedORSetState:
    """bool[..., E, T] planes -> uint32[..., E, W] words (lossless)."""
    t = spec.n_tokens
    pad = spec.n_words * 32 - t

    def pack_plane(plane):
        p = jnp.pad(plane.astype(jnp.uint32), [(0, 0)] * (plane.ndim - 1) + [(0, pad)])
        p = p.reshape(p.shape[:-1] + (spec.n_words, 32))
        weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
        return jnp.sum(p * weights, axis=-1, dtype=jnp.uint32)

    return PackedORSetState(
        exists=pack_plane(dense.exists),
        removed=pack_plane(dense.removed & dense.exists),
    )


def unpack_orset(spec: PackedORSetSpec, packed: PackedORSetState) -> ORSetState:
    t = spec.n_tokens

    def unpack_plane(words):
        bits = (words[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
        flat = bits.reshape(words.shape[:-1] + (spec.n_words * 32,))
        return flat[..., :t].astype(bool)

    return ORSetState(
        exists=unpack_plane(packed.exists), removed=unpack_plane(packed.removed)
    )
