"""Fused multi-round gossip: amortize dispatch + convergence checks.

One host dispatch per gossip round costs a device round-trip and a separate
convergence reduction; at small per-round runtimes (the common case once
states are bit-packed) dispatch dominates. ``fused_gossip_rounds`` runs a
block of rounds inside a single jitted ``lax.fori_loop`` and reports
whether the block changed anything — the convergence driver then works in
blocks: still O(diameter) total rounds, but 1/block_size the dispatches
and equality reductions."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..mesh.gossip import gossip_round


def fused_gossip_rounds(codec, spec, states, neighbors, n_rounds: int, edge_mask=None):
    """Run ``n_rounds`` pull-gossip rounds in one compiled computation.
    Returns ``(new_states, changed)`` where ``changed`` is a scalar bool
    (any replica's state differs from entry — the block-level residual)."""

    def body(_, s):
        return gossip_round(codec, spec, s, neighbors, edge_mask)

    out = jax.lax.fori_loop(0, n_rounds, body, states)
    eq = jax.vmap(lambda a, b: codec.equal(spec, a, b))(states, out)
    return out, ~jnp.all(eq)
