"""Fused multi-round gossip: amortize dispatch + convergence checks.

One host dispatch per gossip round costs a device round-trip and a separate
convergence reduction; at small per-round runtimes (the common case once
states are bit-packed) dispatch dominates. ``fused_gossip_rounds`` runs a
block of rounds inside a single jitted ``lax.fori_loop`` and reports
whether the block changed anything — the convergence driver then works in
blocks: still O(diameter) total rounds, but 1/block_size the dispatches
and equality reductions."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..mesh.gossip import _tree_where, gossip_round, gossip_round_grouped


def fused_gossip_rounds(codec, spec, states, neighbors, n_rounds: int, edge_mask=None):
    """Run ``n_rounds`` pull-gossip rounds in one compiled computation.
    Returns ``(new_states, changed)`` where ``changed`` is a scalar bool
    (any replica's state differs from entry — the block-level residual)."""

    def body(_, s):
        return gossip_round(codec, spec, s, neighbors, edge_mask)

    out = jax.lax.fori_loop(0, n_rounds, body, states)
    eq = jax.vmap(lambda a, b: codec.equal(spec, a, b))(states, out)
    return out, ~jnp.all(eq)


def fused_gossip_rounds_count(
    codec, spec, states, neighbors, n_rounds: int, edge_mask=None
):
    """Like :func:`fused_gossip_rounds` but returns ``(new_states,
    n_productive)`` — the number of rounds in the block that changed any
    replica. Gossip is monotone and deterministic, so productive rounds
    are a prefix of the block: ``n_productive < n_rounds`` means the fixed
    point was reached INSIDE this block and the exact global
    rounds-to-convergence is the running sum of ``n_productive`` — no
    rewind/replay needed, and the entry states don't have to be kept
    alive for a block-level equality (roughly one full population copy of
    HBM saved vs the rewind scheme at bench scale)."""

    def body(_, carry):
        s, prod = carry
        new = gossip_round(codec, spec, s, neighbors, edge_mask)
        eq = jax.vmap(lambda a, b: codec.equal(spec, a, b))(s, new)
        return new, prod + jnp.where(jnp.all(eq), 0, 1)

    out, prod = jax.lax.fori_loop(
        0, n_rounds, body, (states, jnp.zeros((), jnp.int32))
    )
    return out, prod


def fused_chaos_rounds(codec, spec, states, neighbors, masks):
    """Run one WINDOW of a chaos schedule — ``masks: bool[T, R, K]``,
    one edge-alive mask per round (the per-round compilation a
    ``chaos.ChaosSchedule`` emits) — inside a single ``lax.fori_loop``
    dispatch. This is the CODEC-LEVEL member of this module's family
    (like :func:`fused_gossip_rounds` / :func:`fused_frontier_rounds`):
    the entry point for populations managed outside a
    ``ReplicatedRuntime``. The runtime-layer twin is
    ``chaos.ChaosRuntime.fused_steps``, which runs the runtime's FULL
    step (dataflow sweep + triggers + per-var residuals) under the same
    stacked-mask shape — equivalence between the two is pinned by
    tests/chaos/test_schedule.py. The schedule rides as a TRACED operand: the whole fault
    timeline (partitions opening and healing, flaky links flickering,
    slow shards throttling) compiles into the SAME masked
    :func:`~lasp_tpu.mesh.gossip.gossip_round` kernel the dense engine
    uses — no chaos-specific collective path, so the per-round states
    are bit-identical to stepping the masks one host dispatch at a time
    (asserted by tests/chaos/test_schedule.py).

    Returns ``(new_states, residuals)`` with ``residuals: int32[T]`` =
    replica rows each round changed — the same residual contract as the
    engine step, so healing (a zero tail after the last fault clears)
    is visible without per-round host syncs."""
    masks = jnp.asarray(masks)
    n_rounds = masks.shape[0]

    def body(i, carry):
        s, res = carry
        new = gossip_round(codec, spec, s, neighbors, masks[i])
        changed = jax.vmap(lambda a, b: ~codec.equal(spec, a, b))(s, new)
        return new, res.at[i].set(jnp.sum(changed.astype(jnp.int32)))

    return jax.lax.fori_loop(
        0, n_rounds, body, (states, jnp.zeros((n_rounds,), jnp.int32))
    )


def fused_gossip_rounds_grouped(
    codec, spec, states, neighbors, n_rounds: int, edge_mask=None
):
    """Grouped (megabatch) member of the fused family: ``states`` leaves
    are ``[G, R, ...]`` — a dispatch-plan group's stacked same-codec
    variables (``mesh.plan``) — and ``n_rounds`` rounds run vmapped over
    the group axis inside ONE ``lax.fori_loop`` dispatch. Returns
    ``(new_states, changed: bool[G])``, the per-member block residual
    (which members the block changed at all) — the grouped twin of
    :func:`fused_gossip_rounds`'s scalar. Bit-identical per member to
    running :func:`fused_gossip_rounds` on each variable alone
    (tests/mesh/test_plan.py)."""

    def body(_, s):
        return gossip_round_grouped(codec, spec, s, neighbors, edge_mask)

    out = jax.lax.fori_loop(0, n_rounds, body, states)
    eq = jax.vmap(
        jax.vmap(lambda a, b: codec.equal(spec, a, b))
    )(states, out)
    return out, ~jnp.all(eq, axis=1)


def fused_chaos_rounds_grouped(codec, spec, states, neighbors, masks):
    """Grouped twin of :func:`fused_chaos_rounds`: one chaos WINDOW
    (``masks: bool[T, R, K]``, one edge-alive mask per round) over one
    dispatch-plan GROUP (``states`` leaves ``[G, R, ...]``) in a single
    ``lax.fori_loop`` dispatch — the stacked-mask × stacked-variable
    composition. The mask stack rides as a traced operand exactly as in
    the per-var kernel; the group axis batches the masked joins, so
    per-round per-member states are bit-identical to per-var stepping
    (tests/mesh/test_plan.py pins it against
    :func:`fused_chaos_rounds`).

    Returns ``(new_states, residuals: int32[T, G])`` — replica rows each
    round changed, per member: the same residual contract as the engine
    step, scattered back per variable by the caller."""
    masks = jnp.asarray(masks)
    n_rounds = masks.shape[0]
    n_group = jax.tree_util.tree_leaves(states)[0].shape[0]

    def body(i, carry):
        s, res = carry
        new = gossip_round_grouped(codec, spec, s, neighbors, masks[i])
        changed = jax.vmap(
            jax.vmap(lambda a, b: ~codec.equal(spec, a, b))
        )(s, new)
        return new, res.at[i].set(jnp.sum(changed.astype(jnp.int32), axis=1))

    return jax.lax.fori_loop(
        0, n_rounds, body,
        (states, jnp.zeros((n_rounds, n_group), jnp.int32)),
    )


def fused_dataflow_rounds(round_fn, states, tables, n_dsts: int,
                          max_rounds, flight_rounds: int = 0):
    """The dataflow propagate megakernel's fixed-point loop: run the
    compiled leveled Jacobi sweep (``dataflow.plan.make_round_fn`` —
    same-signature edge groups stacked and vmapped, merges per dst in
    edge-index order) inside ONE ``lax.while_loop`` until the per-dst
    change flags are all-false or ``max_rounds`` sweeps have run. The
    whole k-sweep fixed point is one device dispatch — the host loop it
    replaces paid a dispatch plus a changed-flags sync per sweep.

    Returns ``(new_states, per_dst_rounds: int32[n_dsts], sweeps:
    int32, pending: bool)`` — ``per_dst_rounds[i]`` counts the sweeps
    that changed ``dst_order[i]`` (the causal event log's per-dst
    summary for the fused window), ``sweeps`` the sweeps executed, and
    ``pending`` whether the budget ran out while flags were still
    flipping (the caller surfaces that as the same non-convergence
    error the host loop raises). Gossip's monotone-join argument makes
    productive sweeps a prefix: when ``pending`` is False the last
    sweep is the (unproductive) convergence check, so the per-edge
    path's round count is exactly ``sweeps - 1``. ``max_rounds`` may be
    a TRACED scalar (the compiler passes the budget as an operand so
    one executable serves every budget a caller names).

    With ``flight_rounds=K > 0`` the loop also carries a modulo-``K``
    flight ring (``telemetry.device``) of per-sweep changed flags —
    ``int32[K, n_dsts]``, sweep ``i`` at slot ``i % K`` — and returns
    it as a fifth output: the per-round record the fused window's
    causal-log summary used to collapse."""
    if flight_rounds:
        from ..telemetry.device import ring_init, ring_write

        def cond(carry):
            _s, _counts, i, go, _ring = carry
            return go & (i < max_rounds)

        def body(carry):
            s, counts, i, _go, ring = carry
            new, changed = round_fn(s, tables)
            flags = changed.astype(jnp.int32)
            return (new, counts + flags, i + 1, jnp.any(changed),
                    ring_write(ring, i, flags))

        return jax.lax.while_loop(
            cond, body,
            (states, jnp.zeros((n_dsts,), jnp.int32), jnp.int32(0),
             jnp.bool_(True), ring_init(flight_rounds, n_dsts)),
        )

    def cond(carry):
        _s, _counts, i, go = carry
        return go & (i < max_rounds)

    def body(carry):
        s, counts, i, _go = carry
        new, changed = round_fn(s, tables)
        return new, counts + changed.astype(jnp.int32), i + 1, jnp.any(changed)

    return jax.lax.while_loop(
        cond, body,
        (states, jnp.zeros((n_dsts,), jnp.int32), jnp.int32(0),
         jnp.bool_(True)),
    )


def fused_frontier_rounds(
    codec, spec, states, neighbors, frontier, n_rounds: int, edge_mask=None
):
    """Frontier-carried twin of :func:`fused_gossip_rounds_count`: run up
    to ``n_rounds`` pull rounds inside one ``lax.while_loop`` with a
    device-resident dirty mask ``frontier: bool[R]``, EXITING EARLY the
    moment the frontier empties (nothing can change any further round —
    post-convergence no-ops are never executed, without a host probe).

    Each round only rows reachable from the frontier may change
    (``reach[r] = any(frontier[neighbors[r, :]])``, dead edges excluded
    under ``edge_mask``); the new frontier is exactly the rows the round
    inflated. Per-round compute here stays dense (the masked select is
    for exact frontier semantics, not work skipping — this variant
    serves plainly auto-sharded populations, where a host-scheduled row
    gather would fight the partitioner; the work-skipping host path is
    ``mesh.gossip.gossip_round_rows``, and PARTITIONED meshes have the
    real thing: ``mesh.shard_gossip.partitioned_frontier_round_fn``
    moves only dirty cut rows over the wire with the interior joins
    overlapping the exchange). Returns ``(new_states, new_frontier,
    n_productive)``."""

    def cond(carry):
        _s, f, i = carry
        return (i < n_rounds) & jnp.any(f)

    def body(carry):
        s, f, i = carry
        fanin = f[neighbors]  # [R, K]
        if edge_mask is not None:
            fanin = fanin & edge_mask
        reach = jnp.any(fanin, axis=1)
        new = gossip_round(codec, spec, s, neighbors, edge_mask)
        new = _tree_where(reach, new, s)
        changed = jax.vmap(lambda a, b: ~codec.equal(spec, a, b))(s, new)
        return new, changed, i + 1

    out, f, i = jax.lax.while_loop(
        cond, body, (states, frontier, jnp.int32(0))
    )
    return out, f, i
