"""Pallas TPU kernel: fused gather + lattice-join gossip round.

The XLA lowering of a gossip round materializes K gathered neighbor arrays
(one ``[R, D]`` copy per fan-in edge per plane) in HBM before the OR joins
fuse. This kernel streams instead: for each replica-block, the neighbor
rows are DMA'd directly from the full HBM-resident state into VMEM scratch
and joined there — per round, HBM sees K row *reads* and one row *write*
per replica per plane, never an intermediate gathered array.

Shapes: packed planes ride as ``uint32[R, D//128, 128]`` (``D`` =
n_elems * n_words lane-padded to 128; the leading replica axis must stay
OUTSIDE the (8, 128)-tiled trailing pair, because Mosaic only allows
single-row dynamic HBM slices along untiled batch dimensions) with
``neighbors int32[R, K]`` blocked into SMEM per replica-block (a whole-table
scalar prefetch would overflow SMEM at million-replica populations). Both
OR-Set planes are joined in one kernel launch since they share the
neighbor gather.

Correctness is pinned against :func:`lasp_tpu.mesh.gossip.gossip_round` in
interpret mode on CPU and compiled on TPU.

SHIPPING PATH + MEASURED GATE: ``bench_scenarios.orset_anti_entropy``
(the bench.py headline and the ``orset_100k`` scenario) autotunes between
this kernel and the XLA gather+join per run — it times one fused block of
each on the actual hardware and ships the winner; both timings are
recorded in the result (``impl_block_seconds``) and surface in the driver
benchmark artifact. ``bench_pallas.py`` remains the standalone sweep over
row-width configs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128


def _round_kernel(
    nbr_ref,  # int32[B, K] — this block's neighbor rows (SMEM)
    exists_blk,  # uint32[B, D] — own rows of the exists plane
    removed_blk,  # uint32[B, D] — own rows of the removed plane
    exists_hbm,  # uint32[R, D] — full plane (ANY/HBM, DMA source)
    removed_hbm,  # uint32[R, D]
    out_exists,  # uint32[B, D]
    out_removed,  # uint32[B, D]
    scratch_e,  # VMEM uint32[K, D]
    scratch_r,  # VMEM uint32[K, D]
    sem_e,  # DMA sems [K]
    sem_r,  # DMA sems [K]
    *,
    block: int,
    k: int,
):
    del block
    def row_body(r, _):
        # launch the K neighbor-row fetches for both planes, then join
        def start(j, __):
            idx = nbr_ref[r, j]
            pltpu.make_async_copy(
                exists_hbm.at[idx], scratch_e.at[j], sem_e.at[j]
            ).start()
            pltpu.make_async_copy(
                removed_hbm.at[idx], scratch_r.at[j], sem_r.at[j]
            ).start()
            return 0

        jax.lax.fori_loop(0, k, start, 0)

        def wait(j, acc):
            acc_e, acc_r = acc
            pltpu.make_async_copy(
                exists_hbm.at[nbr_ref[r, j]], scratch_e.at[j], sem_e.at[j]
            ).wait()
            pltpu.make_async_copy(
                removed_hbm.at[nbr_ref[r, j]], scratch_r.at[j], sem_r.at[j]
            ).wait()
            return (acc_e | scratch_e[j], acc_r | scratch_r[j])

        acc_e, acc_r = jax.lax.fori_loop(
            0, k, wait, (exists_blk[r], removed_blk[r])
        )
        out_exists[r, :] = acc_e
        out_removed[r, :] = acc_r
        return 0

    jax.lax.fori_loop(0, out_exists.shape[0], row_body, 0)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def pallas_gossip_round(exists, removed, neighbors, block: int = 8, interpret: bool = False):
    """One pull-gossip round over packed OR-Set planes.

    ``exists``/``removed``: uint32[R, D] with D a multiple of 128 and R a
    multiple of ``block``; ``neighbors``: int32[R, K]. Returns the joined
    planes (same shapes)."""
    r_total, d = exists.shape
    k = neighbors.shape[1]
    assert d % LANE == 0, f"lane dim {d} must be a multiple of {LANE}"
    assert r_total % block == 0, f"{r_total} rows not divisible by block {block}"
    w = d // LANE
    # 3D layout: replica axis outside the (8, 128)-tiled trailing pair so
    # per-row dynamic HBM slices are legal at any index
    e3 = exists.reshape(r_total, w, LANE)
    m3 = removed.reshape(r_total, w, LANE)

    kernel = functools.partial(_round_kernel, block=block, k=k)
    out_e, out_r = pl.pallas_call(
        kernel,
        grid=(r_total // block,),
        in_specs=[
            pl.BlockSpec((block, k), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((k, w, LANE), jnp.uint32),
            pltpu.VMEM((k, w, LANE), jnp.uint32),
            pltpu.SemaphoreType.DMA((k,)),
            pltpu.SemaphoreType.DMA((k,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r_total, w, LANE), jnp.uint32),
            jax.ShapeDtypeStruct((r_total, w, LANE), jnp.uint32),
        ],
        interpret=interpret,
    )(neighbors, e3, m3, e3, m3)
    return out_e.reshape(r_total, d), out_r.reshape(r_total, d)


def flatten_plane(plane, lane: int = LANE):
    """``uint32[R, E, W] -> uint32[R, D]`` with D lane-padded."""
    r = plane.shape[0]
    flat = plane.reshape(r, -1)
    d = flat.shape[1]
    pad = (-d) % lane
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat, d


def unflatten_plane(flat, shape):
    r, e, w = shape
    return flat[:, : e * w].reshape(r, e, w)
