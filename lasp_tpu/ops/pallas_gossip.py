"""Pallas TPU kernel: fused gather + lattice-join gossip round.

The XLA lowering of a gossip round materializes K gathered neighbor arrays
(one ``[R, D]`` copy per fan-in edge per plane) in HBM before the OR joins
fuse. This kernel streams instead: for each replica-block, the neighbor
rows are DMA'd directly from the full HBM-resident state into VMEM scratch
and joined there — per round, HBM sees K row *reads* and one row *write*
per replica per plane, never an intermediate gathered array.

Shapes: packed planes ride as ``uint32[R, D//128, 128]`` (``D`` =
n_elems * n_words lane-padded to 128; the leading replica axis must stay
OUTSIDE the (8, 128)-tiled trailing pair, because Mosaic only allows
single-row dynamic HBM slices along untiled batch dimensions) with
``neighbors int32[R, K]`` blocked into SMEM per replica-block (a whole-table
scalar prefetch would overflow SMEM at million-replica populations). Both
OR-Set planes are joined in one kernel launch since they share the
neighbor gather.

Correctness is pinned against :func:`lasp_tpu.mesh.gossip.gossip_round` in
interpret mode on CPU and compiled on TPU.

SHIPPING PATH + MEASURED GATE: ``bench_scenarios.orset_anti_entropy``
(the bench.py headline and the ``orset_100k`` scenario) autotunes between
this kernel and the XLA gather+join per run — it times one fused block of
each on the actual hardware and ships the winner; both timings are
recorded in the result (``impl_block_seconds``) and surface in the driver
benchmark artifact. ``bench_pallas.py`` remains the standalone sweep over
row-width configs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128


def _round_kernel(
    nbr_ref,  # int32[B, K] — this block's neighbor rows (SMEM)
    exists_blk,  # uint32[B, D] — own rows of the exists plane
    removed_blk,  # uint32[B, D] — own rows of the removed plane
    exists_hbm,  # uint32[R, D] — full plane (ANY/HBM, DMA source)
    removed_hbm,  # uint32[R, D]
    out_exists,  # uint32[B, D]
    out_removed,  # uint32[B, D]
    scratch_e,  # VMEM uint32[K, D]
    scratch_r,  # VMEM uint32[K, D]
    sem_e,  # DMA sems [K]
    sem_r,  # DMA sems [K]
    *,
    block: int,
    k: int,
):
    del block
    def row_body(r, _):
        # launch the K neighbor-row fetches for both planes, then join
        def start(j, __):
            idx = nbr_ref[r, j]
            pltpu.make_async_copy(
                exists_hbm.at[idx], scratch_e.at[j], sem_e.at[j]
            ).start()
            pltpu.make_async_copy(
                removed_hbm.at[idx], scratch_r.at[j], sem_r.at[j]
            ).start()
            return 0

        jax.lax.fori_loop(0, k, start, 0)

        def wait(j, acc):
            acc_e, acc_r = acc
            pltpu.make_async_copy(
                exists_hbm.at[nbr_ref[r, j]], scratch_e.at[j], sem_e.at[j]
            ).wait()
            pltpu.make_async_copy(
                removed_hbm.at[nbr_ref[r, j]], scratch_r.at[j], sem_r.at[j]
            ).wait()
            return (acc_e | scratch_e[j], acc_r | scratch_r[j])

        acc_e, acc_r = jax.lax.fori_loop(
            0, k, wait, (exists_blk[r], removed_blk[r])
        )
        out_exists[r, :] = acc_e
        out_removed[r, :] = acc_r
        return 0

    jax.lax.fori_loop(0, out_exists.shape[0], row_body, 0)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def pallas_gossip_round(exists, removed, neighbors, block: int = 8, interpret: bool = False):
    """One pull-gossip round over packed OR-Set planes.

    ``exists``/``removed``: uint32[R, D] with D a multiple of 128;
    ``neighbors``: int32[R, K]. Returns the joined planes (same shapes).
    Arbitrary replica counts are legal: the replica axis pads to the
    ``block`` boundary inside this wrapper (pad rows are zero rows that
    gather row 0 — real rows never reference them, and their outputs are
    sliced off, which masks them out of the scatter), so any population
    can ship the Pallas arm instead of silently falling back to XLA on a
    divisibility assert."""
    r_total, d = exists.shape
    k = neighbors.shape[1]
    assert d % LANE == 0, f"lane dim {d} must be a multiple of {LANE}"
    pad_rows = (-r_total) % block
    if pad_rows:
        exists = jnp.pad(exists, ((0, pad_rows), (0, 0)))
        removed = jnp.pad(removed, ((0, pad_rows), (0, 0)))
        neighbors = jnp.concatenate(
            [neighbors,
             jnp.zeros((pad_rows, k), dtype=neighbors.dtype)], axis=0
        )
    r_padded = r_total + pad_rows
    w = d // LANE
    # 3D layout: replica axis outside the (8, 128)-tiled trailing pair so
    # per-row dynamic HBM slices are legal at any index
    e3 = exists.reshape(r_padded, w, LANE)
    m3 = removed.reshape(r_padded, w, LANE)

    kernel = functools.partial(_round_kernel, block=block, k=k)
    out_e, out_r = pl.pallas_call(
        kernel,
        grid=(r_padded // block,),
        in_specs=[
            pl.BlockSpec((block, k), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (block, w, LANE), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        scratch_shapes=[
            pltpu.VMEM((k, w, LANE), jnp.uint32),
            pltpu.VMEM((k, w, LANE), jnp.uint32),
            pltpu.SemaphoreType.DMA((k,)),
            pltpu.SemaphoreType.DMA((k,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r_padded, w, LANE), jnp.uint32),
            jax.ShapeDtypeStruct((r_padded, w, LANE), jnp.uint32),
        ],
        interpret=interpret,
    )(neighbors, e3, m3, e3, m3)
    out_e = out_e.reshape(r_padded, d)[:r_total]
    out_r = out_r.reshape(r_padded, d)[:r_total]
    return out_e, out_r


# ---------------------------------------------------------------------------
# row-sparse frontier gossip: signature-specialized gather–join–scatter
# ---------------------------------------------------------------------------
#
# The frontier scheduler's hot kernel (``gossip.gossip_round_rows`` /
# ``_grouped``) is SpMM-shaped: gather the K neighbor rows of every
# dirty-bucket slot, fold the lattice join, scatter the joined rows
# back. The XLA lowering materializes one ``[F, ...]`` gathered copy per
# fan-in column per leaf in HBM before the joins fuse; this kernel
# streams instead — per slot, the (K+1) rows DMA straight from the
# HBM-resident ``[G, R, ...]`` planes into double-buffered VMEM scratch
# (slot i+1's copies are in flight while slot i joins — the JITSPMM
# move of specializing the instruction stream per sparsity signature),
# the join runs in VMEM, and the joined rows land in a ``[G, F, ...]``
# output that the wrapper scatters back in place with the same donated
# ``.at[rows].set`` the XLA kernel uses. The scatter stays OUTSIDE the
# kernel deliberately: rows inside one bucket may name each other as
# neighbors, and the round's contract is that every gather reads the
# PRE-round state — an in-kernel in-place write would let a later grid
# step observe an earlier step's join (schedule-dependent, not
# bit-identical). "Fast and Fusiest" (PAPERS.md) grounds exactly this
# fusion boundary: fuse gather+join (the bandwidth-bound stages), leave
# the order-sensitive scatter to the donated XLA epilogue.
#
# Supported join families (``rows_plan_of``):
#
# - ``leafwise`` — codecs declaring ``leafwise_join`` ("or"/"max"): the
#   fold is the same elementwise op on every leaf (G-Set, G-Counter,
#   packed/flat OR-Sets). Dead edges are SKIPPED rather than
#   substituted with the own row: or/max are absorbing on the
#   already-accumulated own state, so the result is bit-identical to
#   the XLA round's own-state substitution.
# - ``vclock`` — the (clock, dots) dot-matrix pair (OR-SWOT): the
#   ``lattice.dots.merge_dots`` survival rule evaluated leafwise in
#   VMEM (clock rides as ``[1, A]`` so the ``dots > clock`` compare
#   broadcasts across the element sublanes). Skipping a dead edge is
#   again exact: every reachable state satisfies ``dots <= own clock``
#   pointwise, under which ``merge(acc, old) == acc`` bit-for-bit.
#
# The per-slot CHANGED flag is a raw leaf-inequality reduction computed
# in-kernel (SMEM output). On reachable states this equals
# ``~codec.equal``: the packed codecs' masked removed-plane compare
# coincides with raw equality because ``removed ⊆ exists`` is an
# invariant of every constructor/op/merge (asserted across codecs by
# tests/ops/test_pallas_rows.py). The PR5 pad-slot contract holds
# as in the XLA kernel: pad slots compute and scatter the JOINED value
# (duplicate slots write identical values by idempotence; out-of-reach
# rows join to themselves by the frontier invariant) and ``valid``
# only gates the changed accounting — grouped CHANGED stays
# bit-identical.

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class RowsPlan:
    """How one codec's state maps onto the row-sparse kernel."""

    kind: str  # "leafwise" | "vclock"
    op: "str | None" = None  # leafwise op ("or" | "max")


def rows_plan_of(codec, spec, states) -> "RowsPlan | None":
    """The kernel plan for ``codec``'s states, or None when this codec
    cannot ride the Pallas row-sparse arm (the dispatch race then keeps
    the XLA kernel — e.g. riak_dt_map's embedded-field merge)."""
    del spec
    kind = getattr(codec, "leafwise_join", None)
    if kind in ("or", "max"):
        return RowsPlan(kind="leafwise", op=kind)
    if getattr(states, "_fields", None) == ("clock", "dots"):
        return RowsPlan(kind="vclock")
    return None


def tuned_rows_block(row_bytes: int, bucket: int, fanout: int) -> int:
    """The tuned slots-per-grid-block for one dispatch signature: VMEM
    holds 2 double-buffer sets of (fanout+1) gathered rows plus the
    block's output rows, so wide rows get narrow blocks and narrow rows
    amortize grid overhead across wider ones. Kept a pure function of
    the ``(codec row bytes, bucket, fanout)`` signature so a cached
    variant's configuration is reproducible."""
    budget = 2 << 20  # ~2 MiB of VMEM per signature
    per_slot = max((2 * (int(fanout) + 1) + 1) * max(int(row_bytes), 1), 1)
    fb = max(budget // per_slot, 1)
    fb = 1 << (int(fb).bit_length() - 1)  # floor to a power of two
    fb = max(2, min(fb, 32))
    # never wider than the (pow2-ceil of the) bucket itself
    while fb > max(int(bucket), 1):
        fb >>= 1
    return max(fb, 1)


def _vclock_join(acc, nbr):
    """``lattice.dots.merge_dots`` on VMEM views: ``acc``/``nbr`` are
    ``[clock[1, A], dots[E, A]]``. Same op sequence as the XLA merge, so
    the fold is bit-identical."""
    ca, da = acc
    cb, db = nbr
    clock = jnp.maximum(ca, cb)
    keep_a = (da > 0) & ((da == db) | (da > cb))
    keep_b = (db > 0) & ((db == da) | (db > ca))
    zero = jnp.zeros_like(da)
    dots = jnp.maximum(
        jnp.where(keep_a, da, zero), jnp.where(keep_b, db, zero)
    )
    return [clock, dots]


def _leafwise_join_fn(op_name: str):
    op = jnp.bitwise_or if op_name == "or" else jnp.maximum
    return lambda acc, nbr: [op(a, b) for a, b in zip(acc, nbr)]


def _rows_kernel(*refs, n_leaves: int, k: int, fb: int, masked: bool, join):
    """Gather–join body: grid ``(G, F // fb)``; per slot, DMA the own
    row + K neighbor rows of every leaf into the double-buffered VMEM
    scratch (prefetching slot i+1 while joining slot i), fold the join
    in k order (the XLA kernels' fold order), flag CHANGED, and write
    the joined rows to the block's output."""
    rows_s, nbr_s = refs[0], refs[1]
    i0 = 2
    mask_s = None
    if masked:
        mask_s = refs[2]
        i0 = 3
    planes = refs[i0:i0 + n_leaves]
    outs = refs[i0 + n_leaves:i0 + 2 * n_leaves]
    changed_s = refs[i0 + 2 * n_leaves]
    scr = refs[i0 + 2 * n_leaves + 1:i0 + 3 * n_leaves + 1]
    sems = refs[i0 + 3 * n_leaves + 1:]
    g = pl.program_id(0)

    def dmas(slot, buf):
        """The slot's (K+1) per-leaf row copies (reconstructed
        identically for start and wait — the dense kernel's pattern)."""
        row = rows_s[0, slot]
        cps = []
        for leaf in range(n_leaves):
            cps.append(pltpu.make_async_copy(
                planes[leaf].at[g, row], scr[leaf].at[buf, 0],
                sems[leaf].at[buf, 0],
            ))
        for j in range(k):
            idx = nbr_s[0, slot, j]
            for leaf in range(n_leaves):
                cps.append(pltpu.make_async_copy(
                    planes[leaf].at[g, idx], scr[leaf].at[buf, j + 1],
                    sems[leaf].at[buf, j + 1],
                ))
        return cps

    for c in dmas(0, 0):  # warm-up: slot 0 into buffer 0
        c.start()

    def body(i, _):
        buf = jax.lax.rem(i, 2)

        @pl.when(i + 1 < fb)
        def _prefetch():  # slot i+1 streams while slot i joins
            for c in dmas(i + 1, jax.lax.rem(i + 1, 2)):
                c.start()

        for c in dmas(i, buf):
            c.wait()
        own = [scr[leaf][buf, 0] for leaf in range(n_leaves)]
        acc = list(own)
        for j in range(k):
            nbr = [scr[leaf][buf, j + 1] for leaf in range(n_leaves)]
            merged = join(acc, nbr)
            if masked:
                live = mask_s[0, i, j] != 0
                acc = [jnp.where(live, m, a) for m, a in zip(merged, acc)]
            else:
                acc = merged
        diff = jnp.bool_(False)
        for a, o in zip(acc, own):
            diff = diff | jnp.any(a != o)
        changed_s[0, i] = diff.astype(jnp.int32)
        for leaf in range(n_leaves):
            outs[leaf][0, i] = acc[leaf]
        return 0

    jax.lax.fori_loop(0, fb, body, 0)


def _leaf_views(leaves, kind: str):
    """4D ``[G, R, S1, S2]`` kernel views of grouped state leaves (pure
    reshapes — never a padding copy; a non-lane-multiple flat width
    rides as a single ``[1, d]`` tile row and Mosaic pads lanes
    internally). vclock keeps natural shapes: clock ``[G, R, 1, A]``
    (broadcastable against dots' sublanes), dots ``[G, R, E, A]``."""
    views = []
    for i, leaf in enumerate(leaves):
        g, r = leaf.shape[:2]
        if kind == "vclock":
            if i == 0:  # clock [G, R, A]
                views.append(leaf.reshape(g, r, 1, leaf.shape[2]))
            else:  # dots [G, R, E, A]
                views.append(leaf)
            continue
        d = 1
        for s in leaf.shape[2:]:
            d *= int(s)
        if d % LANE == 0:
            views.append(leaf.reshape(g, r, d // LANE, LANE))
        else:
            views.append(leaf.reshape(g, r, 1, d))
    return views


#: compiled row-sparse kernel variants, keyed per dispatch signature —
#: (kind, op, leaf row shapes+dtypes, K, fb, G, F, masked, interpret) —
#: the ``plan.signature_of`` granularity plus the (block, bucket)
#: tuning; one entry serves every same-signature dispatch.
_ROWS_CALLS: dict = {}
_ROWS_CALL_STATS = {"built": 0, "hits": 0}


def rows_kernel_cache_stats() -> dict:
    """``{"built": n, "hits": n}`` for the signature-specialized kernel
    cache (tests assert same-signature dispatches share one variant)."""
    return dict(_ROWS_CALL_STATS)


def _rows_call(key, *, kind, op, row_shapes, dtypes, g, r, fp, k, fb,
               masked, interpret):
    fn = _ROWS_CALLS.get(key)
    if fn is not None:
        _ROWS_CALL_STATS["hits"] += 1
        return fn
    _ROWS_CALL_STATS["built"] += 1
    n_leaves = len(row_shapes)
    join = _vclock_join if kind == "vclock" else _leafwise_join_fn(op)
    kernel = functools.partial(
        _rows_kernel, n_leaves=n_leaves, k=k, fb=fb, masked=masked,
        join=join,
    )
    in_specs = [
        pl.BlockSpec((1, fb), lambda gi, bi: (gi, bi),
                     memory_space=pltpu.SMEM),  # rows
        pl.BlockSpec((1, fb, k), lambda gi, bi: (gi, bi, 0),
                     memory_space=pltpu.SMEM),  # neighbor rows
    ]
    if masked:
        in_specs.append(
            pl.BlockSpec((1, fb, k), lambda gi, bi: (gi, bi, 0),
                         memory_space=pltpu.SMEM)  # edge-mask slots
        )
    in_specs.extend(
        pl.BlockSpec(memory_space=pl.ANY) for _ in range(n_leaves)
    )
    out_specs = [
        pl.BlockSpec((1, fb) + shape, lambda gi, bi: (gi, bi, 0, 0),
                     memory_space=pltpu.VMEM)
        for shape in row_shapes
    ]
    out_specs.append(
        pl.BlockSpec((1, fb), lambda gi, bi: (gi, bi),
                     memory_space=pltpu.SMEM)  # changed flags
    )
    out_shape = [
        jax.ShapeDtypeStruct((g, fp) + shape, dt)
        for shape, dt in zip(row_shapes, dtypes)
    ]
    out_shape.append(jax.ShapeDtypeStruct((g, fp), jnp.int32))
    scratch = [
        pltpu.VMEM((2, k + 1) + shape, dt)
        for shape, dt in zip(row_shapes, dtypes)
    ]
    scratch.extend(
        pltpu.SemaphoreType.DMA((2, k + 1)) for _ in range(n_leaves)
    )
    fn = pl.pallas_call(
        kernel,
        grid=(g, fp // fb),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )
    _ROWS_CALLS[key] = fn
    return fn


def pallas_gossip_round_rows_grouped(codec, spec, states, neighbors, rows,
                                     valid, edge_mask=None, *,
                                     block: "int | None" = None,
                                     interpret: bool = False):
    """Pallas twin of :func:`lasp_tpu.mesh.gossip.gossip_round_rows_grouped`
    — bit-identical contract: ``states`` leaves ``[G, R, ...]``,
    ``rows: int[G, F]`` (bucket-padded, duplicates legal),
    ``valid: bool[G, F]``; returns ``(new_states, changed: bool[G, F])``.
    Traceable (the runtime jits it with donated states); the kernel
    variant is cached per dispatch signature. ``block`` overrides the
    tuned slots-per-grid-block."""
    plan = rows_plan_of(codec, spec, states)
    if plan is None:
        raise ValueError(
            f"{getattr(codec, 'name', codec)}: no Pallas row-sparse plan "
            "(codec is neither leafwise nor a (clock, dots) pair)"
        )
    leaves, treedef = jax.tree_util.tree_flatten(states)
    g, _r = leaves[0].shape[:2]
    rows = jnp.asarray(rows, jnp.int32)
    f = int(rows.shape[-1])
    row_bytes = sum(
        max(int(np.prod(leaf.shape[2:], dtype=np.int64)), 1)
        * leaf.dtype.itemsize
        for leaf in leaves
    )
    k = int(neighbors.shape[1])
    fb = int(block) if block else tuned_rows_block(row_bytes, f, k)
    fp = -(-f // fb) * fb
    if fp != f:  # pad the bucket to the block boundary with slot-0 dupes
        rows = jnp.concatenate(
            [rows, jnp.broadcast_to(rows[:, :1], (g, fp - f))], axis=1
        )
    nbr = jnp.asarray(neighbors, jnp.int32)[rows]  # [G, Fp, K]
    operands = [rows, nbr]
    masked = edge_mask is not None
    if masked:
        operands.append(jnp.asarray(edge_mask)[rows].astype(jnp.int32))
    views = _leaf_views(leaves, plan.kind)
    row_shapes = tuple(v.shape[2:] for v in views)
    dtypes = tuple(v.dtype for v in views)
    call = _rows_call(
        (plan.kind, plan.op, row_shapes, dtypes, g,
         int(leaves[0].shape[1]), fp, k, fb, masked, bool(interpret)),
        kind=plan.kind, op=plan.op, row_shapes=row_shapes, dtypes=dtypes,
        g=g, r=int(leaves[0].shape[1]), fp=fp, k=k, fb=fb, masked=masked,
        interpret=bool(interpret),
    )
    outs = call(*operands, *views)
    out_rows, changed_i32 = outs[:-1], outs[-1]
    new_leaves = []
    for leaf, out4 in zip(leaves, out_rows):
        nr = out4.reshape((g, fp) + leaf.shape[2:])
        new_leaves.append(
            jax.vmap(lambda x, rr, vv: x.at[rr].set(vv))(leaf, rows, nr)
        )
    new_states = jax.tree_util.tree_unflatten(treedef, new_leaves)
    changed = (changed_i32[:, :f] != 0) & jnp.asarray(valid, bool)
    return new_states, changed


def pallas_gossip_round_rows(codec, spec, states, neighbors, rows,
                             edge_mask=None, valid=None, *,
                             block: "int | None" = None,
                             interpret: bool = False):
    """Pallas twin of :func:`lasp_tpu.mesh.gossip.gossip_round_rows` for
    one ``[R, ...]`` population — the grouped kernel at G=1. ``valid``
    optional exactly as in the XLA kernel (absent = raw changed flags
    over every slot; duplicates' writes are identical by idempotence)."""
    states_g = jax.tree_util.tree_map(lambda x: x[None], states)
    rows_g = jnp.asarray(rows)[None]
    valid_g = (
        jnp.ones(rows_g.shape, bool) if valid is None
        else jnp.asarray(valid)[None]
    )
    new_g, changed = pallas_gossip_round_rows_grouped(
        codec, spec, states_g, neighbors, rows_g, valid_g, edge_mask,
        block=block, interpret=interpret,
    )
    return (
        jax.tree_util.tree_map(lambda x: x[0], new_g), changed[0]
    )


def flatten_plane(plane, lane: int = LANE):
    """``uint32[R, E, W] -> uint32[R, D]`` with D lane-padded."""
    r = plane.shape[0]
    flat = plane.reshape(r, -1)
    d = flat.shape[1]
    pad = (-d) % lane
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat, d


def unflatten_plane(flat, shape):
    r, e, w = shape
    return flat[:, : e * w].reshape(r, e, w)
