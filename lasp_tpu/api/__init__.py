"""Public API (L4): the Lasp verb set (``src/lasp.erl``) — SURVEY.md §2.7."""

from .session import Session

__all__ = ["Session"]
