"""Public API facade: the full Lasp verb set against one session.

TPU rebuild of ``src/lasp.erl`` (exports :26-51). The reference's verbs are
synchronous wrappers that spawn a coordination FSM and block in
``wait_for_reqid`` (``src/lasp.erl:384-392``); here the store is local and
dataflow is bulk-synchronous, so each mutating verb optionally runs the
graph to its fixed point (``auto_propagate``) — which is *stronger* than
the reference's guarantee (its tests need ``timer:sleep`` for dataflow to
catch up; ours are deterministic after ``propagate``).

Per-verb parity (reference ``src/lasp.erl``):

- ``declare/1,2`` :157-170 → :meth:`Session.declare`
- ``update/3`` :180-184 → :meth:`Session.update`
- ``bind/2`` :194-198, ``bind_to/2`` :201-207 → :meth:`bind` / :meth:`bind_to`
- ``read/1,2`` :222-235 (default threshold ``{strict, undefined}``),
  ``read_any/1`` :241-245 → :meth:`read` / :meth:`read_any`
- ``filter/map/fold/union/intersection/product`` :252-321 → same names
- ``wait_needed/1,2`` :331-337 → :meth:`wait_needed`
- ``thread/3`` :327-329 → :meth:`thread` (runs the function once against
  the local store; the reference spawns it on each of N replicas, which the
  mesh layer's replica axis subsumes)
- ``register/4`` :84-86, ``execute/2`` :99-111, ``process/4`` :129-150 →
  program registry (the L5 layer, ``src/lasp_program.erl``)

Replication-facing verbs (``preflist/3``, ``mk_reqid/0``) have no meaning
without the FSM machinery; their role (replica placement) lives in
``lasp_tpu.mesh``.
"""

from __future__ import annotations

from typing import Any, Optional

from ..dataflow import Graph
from ..lattice import Threshold
from ..store import Store, Watch
from ..telemetry import counter, get_monitor, render_prometheus


#: one-time flag for the locality-renumbering note (see
#: Session.replicate): emitted at the FIRST reordering replicate of the
#: process, not per call — it is a heads-up, not an error
_locality_note_emitted = False


def _count_verb(verb: str) -> None:
    counter(
        "session_ops_total",
        help="public Lasp verbs dispatched through Session, by verb",
        verb=verb,
    ).inc()


class Session:
    """One Lasp session: a store + a dataflow graph + a program registry."""

    def __init__(self, n_actors: int = 16, auto_propagate: bool = True):
        self.store = Store(n_actors=n_actors)
        self.graph = Graph(self.store)
        self.auto_propagate = auto_propagate
        self.programs: dict[str, Any] = {}

    # -- variables -----------------------------------------------------------
    def declare(self, type: str = "lasp_ivar", id: Optional[str] = None, **caps) -> str:
        """``lasp:declare/1,2`` (``src/lasp.erl:157-170``)."""
        return self.store.declare(id=id, type=type, **caps)

    def update(self, id: str, op: tuple, actor) -> None:
        """``lasp:update/3`` (``src/lasp.erl:180-184``)."""
        _count_verb("update")
        self.store.update(id, op, actor)
        self._maybe_propagate()

    def bind(self, id: str, state) -> None:
        """``lasp:bind/2`` (``src/lasp.erl:194-198``)."""
        _count_verb("bind")
        self.store.bind(id, state)
        self._maybe_propagate()

    def bind_to(self, dst: str, src: str) -> str:
        """``lasp:bind_to/2`` (``src/lasp.erl:201-207``)."""
        out = self.graph.bind_to(dst, src)
        self._maybe_propagate()
        return out

    # -- reads ---------------------------------------------------------------
    def read(self, id: str, threshold=None) -> Watch:
        """``lasp:read/1,2`` (``src/lasp.erl:222-235``). With no threshold
        the default is "whatever is there" (bottom, non-strict) — note the
        reference's ``read/1`` uses ``{strict, undefined}`` for ivars (wait
        for a bind); pass ``Threshold(None, strict=True)`` for that."""
        _count_verb("read")
        self._maybe_propagate()
        return self.store.read(id, threshold)

    def read_any(self, reads: list) -> Watch:
        """``lasp:read_any/1`` (``src/lasp.erl:241-245``)."""
        self._maybe_propagate()
        return self.store.read_any(reads)

    def wait_needed(self, id: str, threshold=None) -> Watch:
        """``lasp:wait_needed/1,2`` (``src/lasp.erl:331-337``)."""
        return self.store.wait_needed(id, threshold)

    def value(self, id: str):
        """Decoded observable value (``Type:value/1`` on a quorum read)."""
        _count_verb("value")
        self._maybe_propagate()
        return self.store.value(id)

    def metrics_text(self) -> str:
        """Prometheus text exposition of the process-global telemetry
        registry — the in-process twin of the bridge's ``metrics`` verb
        and ``lasp_tpu metrics`` (docs/OBSERVABILITY.md)."""
        return render_prometheus()

    def health(self) -> dict:
        """ConvergenceMonitor snapshot + alerts — the in-process twin of
        the bridge's ``{health}`` verb and ``lasp_tpu top``
        (docs/OBSERVABILITY.md)."""
        return get_monitor().health()

    # -- combinators ---------------------------------------------------------
    def map(self, src: str, fn, dst: Optional[str] = None) -> str:
        out = self.graph.map(src, fn, dst)
        self._maybe_propagate()
        return out

    def filter(self, src: str, fn, dst: Optional[str] = None) -> str:
        out = self.graph.filter(src, fn, dst)
        self._maybe_propagate()
        return out

    def fold(self, src: str, fn, dst: Optional[str] = None) -> str:
        out = self.graph.fold(src, fn, dst)
        self._maybe_propagate()
        return out

    def union(self, left: str, right: str, dst: Optional[str] = None) -> str:
        out = self.graph.union(left, right, dst)
        self._maybe_propagate()
        return out

    def intersection(self, left: str, right: str, dst: Optional[str] = None) -> str:
        out = self.graph.intersection(left, right, dst)
        self._maybe_propagate()
        return out

    def product(self, left: str, right: str, dst: Optional[str] = None) -> str:
        out = self.graph.product(left, right, dst)
        self._maybe_propagate()
        return out

    def thread(self, fn, *args) -> None:
        """``lasp:thread/3`` (``src/lasp.erl:327-329``): run a function
        against the store (the reference spawns it on all N replicas of a
        preflist, ``src/lasp_core.erl:231-235``; the replica axis of the
        mesh layer plays that role here)."""
        fn(*args)

    def propagate(self) -> int:
        """Run the dataflow graph to its fixed point now."""
        return self.graph.propagate()

    def _maybe_propagate(self):
        if self.auto_propagate and self.graph.edges:
            self.graph.propagate()

    # -- replication ---------------------------------------------------------
    def replicate(self, n_replicas: int, neighbors=None, *, topology="ring",
                  fanout: int = 3, seed: int = 0, packed: bool = False,
                  locality: bool = True,
                  **kwargs):
        """Lift this session onto a replicated population — the one-call
        path from the single-store verbs to the mesh layer (the
        reference gets replication implicitly from riak_core; here it is
        explicit and this is the on-ramp). Current variable state seeds
        EVERY replica row; the session's dataflow graph becomes the
        population's per-replica sweep; programs keep working at the
        session level (register mesh-level programs on the returned
        runtime). ``neighbors`` overrides ``topology`` (one of ring /
        random / scale_free) + ``fanout`` + ``seed``; extra kwargs reach
        :class:`~lasp_tpu.mesh.runtime.ReplicatedRuntime` (``packed``,
        ``debug_actors``, ``donate_steps``). Irregular built-in
        topologies are locality-ordered by default (a graph isomorphism)
        so a later ``rt.shard(mesh, partition=True)`` ships the cut, not
        the population. NOTE: the renumbering means replica INDICES no
        longer match the raw builder's (e.g. ``scale_free`` hubs are no
        longer the low indices); the permutation is exposed as
        ``rt.locality_perm`` (``perm[new_index] = builder_index``), and
        the O(R) host-side walk costs a few seconds at 10M replicas.
        ``locality=False`` opts out, and an explicit ``neighbors`` table
        is never reordered."""
        from ..mesh import ReplicatedRuntime
        from ..mesh.topology import (
            locality_order,
            random_regular,
            ring,
            scale_free,
        )

        perm = None
        if neighbors is None:
            builder = {
                "ring": lambda: ring(n_replicas, fanout),
                "random": lambda: random_regular(n_replicas, fanout,
                                                 seed=seed),
                "scale_free": lambda: scale_free(n_replicas, fanout,
                                                 seed=seed),
            }.get(topology)
            if builder is None:
                raise ValueError(
                    f"unknown topology {topology!r} "
                    "(ring | random | scale_free)"
                )
            neighbors = builder()
            if locality and topology != "ring":
                perm, neighbors = locality_order(neighbors)
                global _locality_note_emitted
                if not _locality_note_emitted:
                    _locality_note_emitted = True
                    import warnings

                    warnings.warn(
                        "Session.replicate(locality=True) renumbers the "
                        f"{topology!r} topology's replica indices (a graph "
                        "isomorphism that keeps sharded gossip's cut "
                        "small); experiments keyed to the raw builder's "
                        "indices (e.g. scale_free hubs at low ids) must "
                        "translate through rt.locality_perm, or pass "
                        "locality=False. This note prints once per "
                        "process (docs/GUIDE.md §replication).",
                        UserWarning,
                        stacklevel=2,
                    )
        rt = ReplicatedRuntime(
            self.store, self.graph, n_replicas, neighbors,
            packed=packed, **kwargs,
        )
        # builder-index of each replica row (None when no reordering
        # happened) — experiments keyed to raw builder indices translate
        # through this
        rt.locality_perm = perm
        return rt

    def nemesis(self, runtime, preset: str, *, seed: int = 0,
                rounds: int = 12, checkpoint: "str | None" = None,
                **kwargs):
        """Wrap a replicated runtime (from :meth:`replicate`) in a
        :class:`~lasp_tpu.chaos.ChaosRuntime` driving a preset fault
        timeline — the session-level on-ramp to the chaos mesh
        (docs/RESILIENCE.md):

        >>> rt = session.replicate(64)
        >>> chaos = session.nemesis(rt, "ring-cut", seed=3)
        >>> report = chaos.soak()          # rounds_to_heal, repair bytes

        ``preset`` is one of :data:`lasp_tpu.chaos.PRESETS` (ring-cut /
        rolling-crash / flaky-links / slow-shard / delay-links); extra
        kwargs reach the preset builder (drop rates, crash counts, …);
        ``checkpoint`` backs ``Restore(source="checkpoint")`` rows. The
        soak outcome lands in :meth:`health` under ``chaos``."""
        from ..chaos import ChaosRuntime, nemesis as build_nemesis

        _count_verb("nemesis")
        schedule = build_nemesis(
            preset, runtime.n_replicas, runtime._host_neighbors,
            seed=seed, rounds=rounds, **kwargs,
        )
        return ChaosRuntime(runtime, schedule, checkpoint=checkpoint)

    def quorum(self, runtime, *, n: int = 3, r: int = 2, w: int = 2,
               hints: "str | None" = None, **kwargs):
        """Wrap a replicated runtime (from :meth:`replicate`) — or a
        :class:`~lasp_tpu.chaos.ChaosRuntime` from :meth:`nemesis` — in
        a :class:`~lasp_tpu.quorum.QuorumRuntime`: the batched
        request-coordination layer (Dynamo-style N/R/W get/put FSMs,
        read-repair, hinted handoff — docs/RESILIENCE.md "Quorum
        coordination"):

        >>> rt = session.replicate(64)
        >>> chaos = session.nemesis(rt, "rolling-crash")
        >>> kv = session.quorum(chaos)
        >>> rid = kv.submit_put("kv", ("add", "x"), "client0")
        >>> kv.step(); kv.result(rid)

        ``n``/``r``/``w`` default to the reference's N=3, R=W=2;
        ``hints`` names a durable hint-log path (default in-memory);
        extra kwargs reach :class:`QuorumRuntime` (``timeout``,
        ``retries``, ``engine``, ``mode``). The coordination report
        lands in :meth:`health` under ``quorum``."""
        from ..quorum import QuorumRuntime

        _count_verb("quorum")
        return QuorumRuntime(runtime, n=n, r=r, w=w, hints=hints,
                             **kwargs)

    def aae(self, runtime, **kwargs):
        """Wrap a replicated runtime (from :meth:`replicate`) — or a
        :class:`~lasp_tpu.chaos.ChaosRuntime` from :meth:`nemesis` — in
        an :class:`~lasp_tpu.aae.AAEScrubber`: active anti-entropy via
        vectorized Merkle hashtrees, pairwise tree exchange, and
        targeted quorum repair (docs/RESILIENCE.md "Active
        anti-entropy"):

        >>> rt = session.replicate(64)
        >>> chaos = session.nemesis(rt, "rolling-crash")
        >>> scrub = session.aae(chaos)      # attaches per-round hooks
        >>> chaos.soak(); scrub.report()    # detections, repairs

        On a chaos runtime the scrubber attaches itself to the engine's
        per-round hooks (detect/repair before each gossip dispatch,
        commit after); on a bare runtime call ``scrub()`` yourself (or
        hand it to ``ServeFrontend(aae=...)`` for background scrubs).
        Extra kwargs reach :class:`AAEScrubber` (``seg_size``,
        ``scrub_every``, ``quorum``, ``auto_attach``). The AAE report
        lands in :meth:`health` under ``aae``."""
        from ..aae import AAEScrubber

        _count_verb("aae")
        return AAEScrubber(runtime, **kwargs)

    def serve(self, runtime, **kwargs):
        """Wrap a replicated runtime (from :meth:`replicate`) — or a
        :class:`~lasp_tpu.chaos.ChaosRuntime` from :meth:`nemesis` — in
        a :class:`~lasp_tpu.serve.ServeFrontend`: the overload-hardened
        ingestion front-end (bounded admission queues, coalesced
        ``update_batch`` megabatches, vectorized threshold fan-out,
        deadline propagation, the degradation ladder —
        docs/SERVING.md):

        >>> rt = session.replicate(64)
        >>> fe = session.serve(rt)
        >>> t = fe.submit_write("kv", ("add", "x"), "client0")
        >>> fe.cycle(); t.status
        'done'

        Extra kwargs reach :class:`ServeFrontend` (``admission``,
        ``gossip_block``, ``coalesce_max``, ``clock``,
        ``write_backup``). The serving report lands in :meth:`health`
        under ``serve``."""
        from ..serve import ServeFrontend

        _count_verb("serve")
        return ServeFrontend(runtime, **kwargs)

    # -- programs (L5, src/lasp_program.erl) ---------------------------------
    def register(self, name: str, program_cls, *args, **kwargs) -> str:
        """``lasp:register/4`` (``src/lasp.erl:84-86``): instantiate a
        program and run its ``init``. The reference ships source code to
        every partition and compiles it there (``src/lasp_vnode.erl:
        276-366``) because BEAM hot-loads code at runtime; a traced Python
        class needs no deployment step."""
        if name in self.programs:
            return name  # idempotent, like the vnode's dets check
        program = program_cls(*args, **kwargs)
        program.init(self)
        self.programs[name] = program
        return name

    def execute(self, name: str):
        """``lasp:execute/2`` (``src/lasp.erl:99-111``): the program's
        current result, decoded, after its ``value`` filter."""
        program = self.programs[name]
        return program.value(program.execute(self))

    def process(self, object, reason, actor) -> None:
        """``lasp:process/4`` (``src/lasp.erl:129-150``): notify every
        registered program of an object event (the riak_kv put/delete/
        handoff hook path)."""
        # snapshot: a program may register NEW programs while processing
        # (the index program auto-creates views, src/lasp_riak_index_
        # program.erl:162-176); like the reference's async create_views,
        # a view registered by this event first sees the NEXT event
        for program in list(self.programs.values()):
            program.process(self, object, reason, actor)
        self._maybe_propagate()
