"""Typed metric registry: counters, gauges, histograms (SURVEY.md §5).

The reference has only lager log lines and per-type ``stats/1``
introspection (``src/lasp_orset.erl:156-192``); production operation of
the TPU build needs first-class, always-on metrics. This registry is the
one sink every layer (store, mesh, dataflow, bridge, CLI) emits into:

- **typed**: a name is registered once with one instrument type; a second
  registration under a different type is a loud ``TypeError`` (the same
  policy as the config's unknown-knob rejection) — no stringly-typed
  drift between emitters;
- **labeled**: one family per name, one series per sorted label set
  (``histogram("merge_seconds", type="lasp_orset")``), the Prometheus
  data model;
- **cheap**: an emission is a dict lookup + a locked integer/float
  update — microseconds, safe to leave on in the hot host paths (the
  device-side kernels are never touched; see docs/OBSERVABILITY.md for
  the measured overhead guard);
- **isolated snapshots**: :meth:`MetricRegistry.snapshot` deep-copies,
  so a scrape observes one coherent point in time.

The process-global default registry is what the module-level
:func:`counter` / :func:`gauge` / :func:`histogram` helpers write to and
what the CLI / bridge export. :func:`set_enabled` flips every helper to
no-op null instruments — the telemetry-off arm of the bench overhead
guard (``bench.py`` / ``tests/telemetry/test_overhead.py``).

Metric names emitted anywhere in ``lasp_tpu`` must appear in the catalog
table of ``docs/OBSERVABILITY.md`` — ``tools/check_metrics_catalog.py``
(Makefile ``verify``) fails on drift in either direction, which is what
keeps the key set stable across PRs.
"""

from __future__ import annotations

import bisect
import threading
from collections.abc import MutableMapping

#: default histogram boundaries, in seconds: spans five decades from
#: 10 µs host-path blips to 10 s convergence runs; +Inf is implicit
DEFAULT_BUCKETS = (
    1e-5, 1e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5, 1.0, 5.0, 10.0,
)


class Counter:
    """Monotonic counter. ``inc`` with a negative delta raises — a
    counter that can go down is a gauge, and a consumer computing rates
    from it would silently produce garbage."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def inc(self, by: "int | float" = 1) -> None:
        if by < 0:
            raise ValueError(f"counter increments must be >= 0, got {by!r}")
        with self._lock:
            self.value += by


class Gauge:
    """Point-in-time value (set/inc/dec)."""

    __slots__ = ("_lock", "value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self.value = 0

    def set(self, value: "int | float") -> None:
        with self._lock:
            self.value = value

    def inc(self, by: "int | float" = 1) -> None:
        with self._lock:
            self.value += by

    def dec(self, by: "int | float" = 1) -> None:
        with self._lock:
            self.value -= by


def _check_buckets(b: tuple) -> None:
    if not b or list(b) != sorted(b) or len(set(b)) != len(b):
        raise ValueError(
            f"histogram buckets must be non-empty, sorted and distinct, "
            f"got {b!r}"
        )


class Histogram:
    """Fixed-boundary histogram (cumulative rendering happens at export;
    storage is per-bucket so observes stay O(log buckets))."""

    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, lock: threading.Lock, buckets=DEFAULT_BUCKETS):
        b = tuple(float(x) for x in buckets)
        _check_buckets(b)
        self._lock = lock
        self.buckets = b
        self.counts = [0] * (len(b) + 1)  # last slot = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, value: "int | float") -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def cumulative(self) -> list:
        """Per-boundary cumulative counts (the ``le`` series, +Inf last)."""
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out


class _NullCounter:
    __slots__ = ()

    def inc(self, by=1) -> None:
        pass


class _NullGauge:
    __slots__ = ()

    def set(self, value) -> None:
        pass

    def inc(self, by=1) -> None:
        pass

    def dec(self, by=1) -> None:
        pass


class _NullHistogram:
    __slots__ = ()

    def observe(self, value) -> None:
        pass


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()

_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricRegistry:
    """One process-wide family table: ``name -> (type, help, series)``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, dict] = {}

    # -- instrument accessors (create-on-first-use) --------------------------
    def counter(self, name: str, help: "str | None" = None, **labels) -> Counter:
        return self._get(name, "counter", help, labels)

    def gauge(self, name: str, help: "str | None" = None, **labels) -> Gauge:
        return self._get(name, "gauge", help, labels)

    def histogram(
        self, name: str, help: "str | None" = None, buckets=None, **labels
    ) -> Histogram:
        return self._get(name, "histogram", help, labels, buckets=buckets)

    def _get(self, name, mtype, help, labels, buckets=None):
        key = _label_key(labels)
        if mtype == "histogram" and buckets is not None:
            # validate BEFORE the family registers: a rejected bucket
            # spec must not leave a poisoned family behind
            _check_buckets(tuple(float(x) for x in buckets))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = {
                    "type": mtype,
                    "help": help or "",
                    # histogram boundaries are a FAMILY property: every
                    # series of one name buckets identically, or the
                    # rendered le-grid would be incoherent. None = the
                    # defaults; an explicit empty tuple is rejected by
                    # the Histogram constructor below
                    "buckets": (
                        tuple(buckets) if buckets is not None
                        else DEFAULT_BUCKETS
                    ),
                    "series": {},
                }
            elif fam["type"] != mtype:
                raise TypeError(
                    f"metric {name!r} is a {fam['type']}, not a {mtype} — "
                    "one instrument type per name"
                )
            inst = fam["series"].get(key)
            if inst is None:
                if mtype == "histogram":
                    inst = Histogram(self._lock, fam["buckets"])
                else:
                    inst = _TYPES[mtype](self._lock)
                fam["series"][key] = inst
        return inst

    # -- introspection -------------------------------------------------------
    def names(self) -> set:
        with self._lock:
            return set(self._families)

    def snapshot(self) -> dict:
        """Deep, point-in-time copy: ``{name: {"type", "help", "series":
        [{"labels": {...}, ...values...}]}}`` — mutating the registry
        after the call never changes a snapshot already taken."""
        out: dict = {}
        with self._lock:
            for name, fam in self._families.items():
                series = []
                for key, inst in fam["series"].items():
                    entry: dict = {"labels": dict(key)}
                    if fam["type"] == "histogram":
                        entry["buckets"] = list(fam["buckets"])
                        entry["counts"] = list(inst.counts)
                        entry["sum"] = inst.sum
                        entry["count"] = inst.count
                    else:
                        entry["value"] = inst.value
                    series.append(entry)
                out[name] = {
                    "type": fam["type"],
                    "help": fam["help"],
                    "series": series,
                }
        return out

    def reset(self) -> None:
        """Drop every family (tests; a long-lived process never calls
        this). Instruments handed out earlier detach — callers must
        re-fetch by name."""
        with self._lock:
            self._families.clear()


# ---------------------------------------------------------------------------
# process-global default registry + enable switch
# ---------------------------------------------------------------------------

_default = MetricRegistry()
_enabled = True
_generation = 0


def get_registry() -> MetricRegistry:
    return _default


def generation() -> int:
    """Bumped by :func:`reset` — hot emitters that CACHE instrument
    objects (the runtime's per-round path) key their cache on this, so
    a test-time reset detaches stale instruments instead of letting
    them increment into the void."""
    return _generation


def set_enabled(flag: bool) -> None:
    """Flip the module-level helpers between live and null instruments
    (the telemetry-off arm of the bench overhead guard). Per-registry
    instruments already held stay live; only helper lookups change."""
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


def counter(name: str, help: "str | None" = None, **labels):
    if not _enabled:
        return NULL_COUNTER
    return _default.counter(name, help, **labels)


def gauge(name: str, help: "str | None" = None, **labels):
    if not _enabled:
        return NULL_GAUGE
    return _default.gauge(name, help, **labels)


def histogram(name: str, help: "str | None" = None, buckets=None, **labels):
    if not _enabled:
        return NULL_HISTOGRAM
    return _default.histogram(name, help, buckets=buckets, **labels)


def reset() -> None:
    global _generation
    _generation += 1
    _default.reset()


import contextlib as _contextlib


@_contextlib.contextmanager
def scratch_registry():
    """Route the module-level helpers to a FRESH registry for the
    duration of the block, then restore the real one — measurement
    harnesses (telemetry.overhead) use this so thousands of synthetic
    emissions never pollute live metrics. The generation bumps on both
    edges, so hot-path instrument caches (ReplicatedRuntime._instruments,
    StepTrace) detach from the scratch registry on exit instead of
    leaking emissions into it."""
    global _default, _generation
    saved = _default
    _default = MetricRegistry()
    _generation += 1
    try:
        yield _default
    finally:
        _default = saved
        _generation += 1


# ---------------------------------------------------------------------------
# typed fixed-key counter groups (the store's per-instance counters)
# ---------------------------------------------------------------------------


class CounterGroup(MutableMapping):
    """A fixed-key mapping of monotone integer counters — the typed
    replacement for ad-hoc ``{"binds": 0, ...}`` dicts (``Store.metrics``,
    the bridge's persisted counters record). Unknown keys raise
    ``KeyError`` at the write site instead of silently forking the schema;
    values must be non-negative ints. ``update`` exists for checkpoint
    restore (absolute values, still type-checked). Compares equal to any
    mapping with the same items (``collections.abc.Mapping`` semantics),
    so persistence round-trip tests keep working."""

    __slots__ = ("_vals",)

    def __init__(self, keys):
        object.__setattr__(self, "_vals", {k: 0 for k in keys})

    def __getitem__(self, key):
        return self._vals[key]

    def __setitem__(self, key, value):
        if key not in self._vals:
            raise KeyError(
                f"unknown counter {key!r} (schema: {sorted(self._vals)})"
            )
        if not isinstance(value, int) or value < 0:
            raise ValueError(
                f"counter {key!r} must be a non-negative int, got {value!r}"
            )
        self._vals[key] = value

    def __delitem__(self, key):
        raise TypeError("CounterGroup keys are fixed")

    def __iter__(self):
        return iter(self._vals)

    def __len__(self):
        return len(self._vals)

    def snapshot(self) -> dict:
        """Plain-dict copy with the stable key schema — what persistence
        layers serialize (see the schema note in bridge/server.py)."""
        return dict(self._vals)

    def __repr__(self):
        return f"CounterGroup({self._vals!r})"
