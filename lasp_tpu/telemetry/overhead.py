"""Telemetry overhead guard: what the always-on registry/span layer
costs per gossip step, as a fraction of the step itself.

The subsystem's contract is "cheap enough to always be on"; this module
is the measurement that keeps the contract honest. ``bench.py`` embeds
the result in its artifact (``detail["telemetry_overhead"]``) and the
``slow``-marked test (tests/telemetry/test_overhead.py) asserts the
fraction stays under 5%.

Methodology — differential wall-clocking of whole steps drowns in
scheduler noise on a loaded host (the telemetry cost is tens of µs
against ms-scale steps, while load bursts move step times by 30%+), so
the two factors are measured separately, each in its robust regime:

1. **numerator** — the exact per-step emission path (the
   ``gossip.round`` span plus ``ReplicatedRuntime._emit_step_telemetry``,
   factored out of ``step()`` for precisely this purpose) is timed in a
   tight loop, enabled minus disabled: a deterministic µs-scale
   difference that a mean over thousands of iterations pins tightly.
   The runtime's instrument cache and the ``StepTrace`` facade are hot,
   exactly as they are mid-run.
2. **denominator** — the step's device dispatch, min over repeated
   timed steps (min discards load bursts, which only ever inflate).

``overhead_frac = emission_cost_per_step / step_seconds``. Telemetry
does no device work, so its cost is purely additive host time and the
ratio is the honest on-vs-off difference a noise-free machine would
measure.
"""

from __future__ import annotations

import time

import numpy as np

from . import events as _events
from . import registry as _registry
from . import roofline as _roofline
from .spans import span


def measure_overhead(
    n_replicas: int = 1024, step_samples: int = 30,
    emission_samples: int = 3000,
) -> dict:
    """Per-step telemetry cost vs step cost on a small gossip
    population; see the module docstring for the methodology.

    Runs inside a SCRATCH registry (``registry.scratch_registry``) so
    the thousands of synthetic emissions never pollute live metrics.
    The ``set_enabled(False)`` windows are process-global while they
    last — run this from a measurement context (the bench child
    process, the slow test), not a live-serving one."""
    with _registry.scratch_registry():
        return _measure(n_replicas, step_samples, emission_samples)


def _measure(n_replicas: int, step_samples: int,
             emission_samples: int) -> dict:
    from ..dataflow import Graph
    from ..mesh import ReplicatedRuntime
    from ..mesh.topology import ring
    from ..store import Store

    prev = _registry.enabled()
    store = Store(n_actors=8)
    v = store.declare(type="lasp_orset", n_elems=64)
    rt = ReplicatedRuntime(store, Graph(store), n_replicas, ring(n_replicas, 2))
    rt.update_batch(
        v, [(r % n_replicas, ("add", f"x{r}"), f"w{r}") for r in range(8)]
    )
    rt.step()  # compile + first dispatch outside the clock

    res_vec = np.zeros((len(rt.var_ids),), dtype=np.int32)

    def emission_pass(flag: bool) -> float:
        """Mean seconds of one emission (span + registry writes) with
        the switch set to ``flag`` — the disabled pass measures the
        residual cost of the guards themselves."""
        _registry.set_enabled(flag)
        try:
            t0 = time.perf_counter()
            for _ in range(emission_samples):
                with span("gossip.round", annotate=True):
                    pass
                rt._emit_step_telemetry(res_vec, 0, 1e-6)
            return (time.perf_counter() - t0) / emission_samples
        finally:
            _registry.set_enabled(prev)
        # (the loop grows trace.rounds by emission_samples entries —
        # a measurement-only runtime, never the caller's)

    emission_on = emission_pass(True)
    emission_off = emission_pass(False)
    per_step_cost = max(0.0, emission_on - emission_off)

    # the causal event log rides inside _emit_step_telemetry (the
    # delivery event + ConvergenceMonitor feed), so per_step_cost above
    # already covers it; this isolates the marginal cost of ONE event
    # emission so the artifact shows the log's own price too
    def event_pass(flag: bool) -> float:
        _registry.set_enabled(flag)
        try:
            t0 = time.perf_counter()
            for _ in range(emission_samples):
                _events.emit("delivery", residual=0, seconds=0.0)
            return (time.perf_counter() - t0) / emission_samples
        finally:
            _registry.set_enabled(prev)

    event_cost = max(0.0, event_pass(True) - event_pass(False))

    _registry.set_enabled(False)
    try:
        step_s = min(
            _timed(rt.step) for _ in range(step_samples)
        )
    finally:
        _registry.set_enabled(prev)

    overhead = per_step_cost / step_s if step_s > 0 else 0.0
    frontier = _measure_frontier(
        step_samples, max(emission_samples // 3, 200)
    )
    ledger = _measure_ledger(
        max(emission_samples // 3, 200), step_s,
        frontier["round_seconds"], frontier["dispatches_per_round"],
    )
    dataflow = _measure_dataflow(
        step_samples, max(emission_samples // 3, 200)
    )
    aae = _measure_aae(step_samples, max(emission_samples // 3, 200))
    flight = _measure_flight(
        step_samples, max(emission_samples // 3, 200)
    )
    return {
        "frontier": frontier,
        "ledger": ledger,
        "dataflow": dataflow,
        "aae": aae,
        "flight": flight,
        "event_emit_cost_s": round(event_cost, 9),
        "event_log": {
            k: _events.stats()[k] for k in ("ring_size", "deep")
        },
        "telemetry_cost_per_step_s": round(per_step_cost, 9),
        "step_seconds": round(step_s, 6),
        "telemetry_on_s": round(step_s + per_step_cost, 6),
        "telemetry_off_s": round(step_s, 6),
        "overhead_frac": round(overhead, 4),
        "n_replicas": n_replicas,
        "step_samples": step_samples,
        "emission_samples": emission_samples,
    }


def _measure_frontier(step_samples: int, emission_samples: int,
                      n_replicas: int = 256, n_vars: int = 48) -> dict:
    """Grouped-dispatch emission guard: the planned frontier round's
    host-side emission (``_emit_frontier_telemetry`` — per-var residual
    + frontier-size gauges over MANY variables, amortized to pre-resolved
    instruments with skip-if-unchanged sets) timed against the planned
    frontier round itself. The many-small-vars store is exactly the
    regime the dispatch plan (``mesh.plan``) accelerates — a faster
    denominator with an O(vars) emission loop is where the 5% budget is
    most at risk, so the guard measures it directly."""
    from ..dataflow import Graph
    from ..mesh import ReplicatedRuntime
    from ..mesh.topology import random_regular
    from ..store import Store

    prev = _registry.enabled()
    store = Store(n_actors=4)
    ids = [
        store.declare(id=f"v{i}", type="lasp_gset", n_elems=8)
        for i in range(n_vars)
    ]
    rt = ReplicatedRuntime(
        store, Graph(store), n_replicas, random_regular(n_replicas, 3, seed=5)
    )
    for i, v in enumerate(ids):
        rt.update_batch(v, [(i % n_replicas, ("add", "x"), f"a{i}")])
    rt.frontier_step()  # compile + warm the grouped kernels + instruments
    # steady-state residual shape: a few HOT vars whose values MOVE
    # every round (alternating vectors force the gauge-set branch — a
    # constant vector would only ever time the skip-if-unchanged path)
    # over a quiescent majority (which prices the amortization itself)
    hot = max(2, n_vars // 8)
    quiet = [0] * (len(rt.var_ids) - hot)
    vecs = ([1] * hot + quiet, [2] * hot + quiet)
    dispatches = max(len(rt._ensure_plan().groups), 1)

    def emission_pass(flag: bool) -> float:
        _registry.set_enabled(flag)
        try:
            t0 = time.perf_counter()
            for k in range(emission_samples):
                with span("gossip.plan_round", annotate=True):
                    pass
                rt._emit_frontier_telemetry(
                    vecs[k & 1], hot, hot, 0, 0, 1e-6,
                    dispatches=dispatches,
                )
            return (time.perf_counter() - t0) / emission_samples
        finally:
            _registry.set_enabled(prev)

    cost = max(0.0, emission_pass(True) - emission_pass(False))

    def one_active_round():
        # re-dirty one row per var first: a converged store's frontier
        # round is a skip-everything no-op, which would be a dishonestly
        # tiny denominator — the guard must price a round that actually
        # dispatches every group
        for i, vid in enumerate(ids):
            rt._mark_dirty_rows(vid, [i % n_replicas])
        rt.frontier_step()

    _registry.set_enabled(False)
    try:
        round_s = min(_timed(one_active_round) for _ in range(step_samples))
    finally:
        _registry.set_enabled(prev)
    return {
        "emission_cost_per_round_s": round(cost, 9),
        "round_seconds": round(round_s, 6),
        "overhead_frac": round(cost / round_s if round_s > 0 else 0.0, 4),
        "n_vars": n_vars,
        "n_replicas": n_replicas,
        "dispatches_per_round": dispatches,
    }


def _measure_dataflow(step_samples: int, emission_samples: int,
                      depth: int = 6) -> dict:
    """Fused-propagate arm of the guard (the ISSUE-8 hot path): one
    ``Graph.propagate`` in fused mode is ONE device dispatch plus the
    emission path — the ``dataflow.propagate`` span,
    ``Graph._emit_propagate_telemetry`` (counters, per-kind accounting,
    the summarizing ``propagate`` event with per-dst changed counts),
    and the megakernel's single ``dataflow_fused`` ledger record. The
    guard prices exactly that path against a fused propagate that
    actually dispatches a multi-sweep fixed point (an OR-Set filter
    chain: constant token space at any depth, so the denominator is the
    steady state, with no interner growth or host table rebuilds inside
    the clock — a token-minting re-add dirties the whole chain each
    sample)."""
    from ..dataflow import Graph
    from ..store import Store

    prev = _registry.enabled()
    store = Store(n_actors=2)
    g = Graph(store)
    src = store.declare(
        id="src", type="lasp_orset", n_elems=4, n_actors=2,
        tokens_per_actor=4 * step_samples + 16,
    )
    cur = src
    for i in range(depth):
        cur = g.filter(cur, lambda t: True, dst=f"f{i}")
    store.update(src, ("add", "x"), "w")
    g.propagate()  # compile + warm the megakernel (the cold dispatch)

    stats = {
        "rounds": depth, "executed": depth + 1,
        "runs": [depth + 1] * len(g.edges), "fused": True,
        "changed_by_dst": {f"f{i}": depth - i for i in range(depth)},
    }
    ledger = _roofline.get_ledger()
    rec = dict(n_replicas=1, fanout=depth, seconds=1e-6, row_bytes=2048,
               window=depth + 1, rounds=depth + 1,
               bytes_moved=2048 * (depth + 1), joins=depth * (depth + 1),
               n_vars=depth)
    # consume the signature's compile-bucket slot outside the clock
    ledger.record("dataflow_fused", "OverheadProbe", **rec)

    def emission_pass(flag: bool) -> float:
        _registry.set_enabled(flag)
        try:
            t0 = time.perf_counter()
            for _ in range(emission_samples):
                with span("dataflow.propagate", annotate=True):
                    pass
                g._emit_propagate_telemetry(stats, 1e-6)
                ledger.record("dataflow_fused", "OverheadProbe", **rec)
            return (time.perf_counter() - t0) / emission_samples
        finally:
            _registry.set_enabled(prev)

    cost = max(0.0, emission_pass(True) - emission_pass(False))

    _registry.set_enabled(False)
    try:
        secs = []
        for _ in range(step_samples):
            # a fresh token on the source inflates it and re-dirties the
            # whole chain: every timed propagate dispatches a real
            # (depth+1)-sweep fixed point, never the clean-mark no-op
            store.update(src, ("add", "x"), "w")
            secs.append(_timed(g.propagate))
        prop_s = min(secs)
    finally:
        _registry.set_enabled(prev)
    return {
        "emission_cost_per_propagate_s": round(cost, 9),
        "propagate_seconds": round(prop_s, 6),
        "overhead_frac": round(cost / prop_s if prop_s > 0 else 0.0, 4),
        "edges": len(g.edges),
        "sweeps_per_propagate": depth + 1,
        "emission_samples": emission_samples,
    }


def _measure_flight(step_samples: int, emission_samples: int,
                    n_replicas: int = 256, block: int = 8) -> dict:
    """In-graph-counters arm of the guard (the flight-recorder
    tentpole): a fused gossip window now carries a modulo-K stats ring
    through its ``lax`` loop (the in-graph cost — priced with a jitted
    microbenchmark of the ring write itself, ride-along vs loop-only)
    and pays one host-side drain per window
    (``ReplicatedRuntime._drain_flight``: decode + monitor feed +
    per-round delivery events + the window-log append — priced enabled
    minus disabled, the standard differential). The budget assertion in
    tests/telemetry/test_overhead.py holds the SUM of both against the
    fused window the instrumentation observes."""
    import jax
    import jax.numpy as jnp

    from ..dataflow import Graph
    from ..mesh import ReplicatedRuntime
    from ..mesh.topology import ring as ring_topo
    from ..store import Store
    from . import device as _device

    prev = _registry.enabled()
    store = Store(n_actors=8)
    v = store.declare(type="lasp_orset", n_elems=64)
    rt = ReplicatedRuntime(
        store, Graph(store), n_replicas, ring_topo(n_replicas, 2)
    )
    rt.update_batch(
        v, [(r % n_replicas, ("add", f"x{r}"), f"w{r}") for r in range(8)]
    )
    rt.begin_fused_steps(block).finish()  # compile + warm (ring carried)

    n_vars = len(rt.var_ids)
    flight_k = _device.flight_rounds()

    # in-graph side: the ride-along ring write per round, isolated in a
    # jitted loop (the fused window itself always carries the ring now,
    # so the delta is measured on the primitive, not by rebuilding a
    # ring-free twin of the whole step closure)
    def loop(with_ring: bool):
        def f(x):
            def body(i, carry):
                acc, rg = carry
                acc = acc + jnp.sum(x) * 0 + i
                if with_ring:
                    rg = _device.ring_write(
                        rg, i, jnp.full((n_vars,), i, jnp.int32)
                    )
                return acc, rg
            return jax.lax.fori_loop(
                0, block, body,
                (jnp.int32(0), _device.ring_init(flight_k, n_vars)),
            )
        return jax.jit(f)

    probe = jnp.zeros((4,), jnp.int32)
    with_r, without_r = loop(True), loop(False)
    jax.block_until_ready(with_r(probe))   # compile outside the clock
    jax.block_until_ready(without_r(probe))
    ring_s = min(
        _timed(lambda: jax.block_until_ready(with_r(probe)))
        for _ in range(step_samples)
    ) - min(
        _timed(lambda: jax.block_until_ready(without_r(probe)))
        for _ in range(step_samples)
    )
    ring_cost_per_window = max(0.0, ring_s)

    # host side: the per-window drain, enabled minus disabled (the
    # disabled pass is the instruments-guard early return)
    host_ring = np.tile(
        np.arange(1, block + 1, dtype=np.int32)[:, None], (1, n_vars)
    )
    host_ring = np.vstack(
        [host_ring, np.zeros((max(flight_k - block, 0), n_vars), np.int32)]
    )

    def drain_pass(flag: bool) -> float:
        _registry.set_enabled(flag)
        try:
            t0 = time.perf_counter()
            for _ in range(emission_samples):
                rt._drain_flight(
                    "fused_block", host_ring, block, True, 1e-6
                )
            return (time.perf_counter() - t0) / emission_samples
        finally:
            _registry.set_enabled(prev)
        # (the loop grows the monitor's curve by block*samples points —
        # a measurement-only runtime, never the caller's)

    drain_cost = max(0.0, drain_pass(True) - drain_pass(False))

    def one_window():
        rt.begin_fused_steps(block).finish()

    _registry.set_enabled(False)
    try:
        window_s = min(_timed(one_window) for _ in range(step_samples))
    finally:
        _registry.set_enabled(prev)
    total = ring_cost_per_window + drain_cost
    return {
        "ring_write_cost_per_window_s": round(ring_cost_per_window, 9),
        "drain_cost_per_window_s": round(drain_cost, 9),
        "window_seconds": round(window_s, 6),
        "overhead_frac": round(
            total / window_s if window_s > 0 else 0.0, 4
        ),
        "flight_rounds": flight_k,
        "block": block,
        "n_replicas": n_replicas,
    }


def _measure_aae(step_samples: int, emission_samples: int,
                 n_replicas: int = 256, n_vars: int = 24) -> dict:
    """Incremental-rehash arm of the guard (the AAE tentpole's hot
    path): with a hash forest attached, every executed round pays one
    ``HashForest.refresh()`` — the incremental tree commit. The
    CONTRACT is that quiescent variables and clean segments cost
    nothing (a dict walk, no device work), so the 5%-budget figure is
    the steady-state refresh priced against an active frontier round;
    the dirty-row arm (gather + hash of the hot rows only) and the
    from-scratch full rebuild ride in the artifact as the incremental-
    vs-full comparison the ``aae_scrub`` bench scenario re-measures at
    its own shapes."""
    from ..aae import HashForest
    from ..dataflow import Graph
    from ..mesh import ReplicatedRuntime
    from ..mesh.topology import random_regular
    from ..store import Store

    prev = _registry.enabled()
    store = Store(n_actors=4)
    ids = [
        store.declare(id=f"v{i}", type="lasp_gset", n_elems=16)
        for i in range(n_vars)
    ]
    rt = ReplicatedRuntime(
        store, Graph(store), n_replicas,
        random_regular(n_replicas, 3, seed=11),
    )
    for i, v in enumerate(ids):
        rt.update_batch(v, [(i % n_replicas, ("add", "x"), f"a{i}")])
    # denominator FIRST, before any forest attaches: the round must not
    # carry the very cost the numerator isolates
    rt.frontier_step()  # compile + warm

    def one_active_round():
        for i, vid in enumerate(ids):
            rt._mark_dirty_rows(vid, [i % n_replicas])
        rt.frontier_step()

    _registry.set_enabled(False)
    try:
        round_s = min(_timed(one_active_round) for _ in range(step_samples))
    finally:
        _registry.set_enabled(prev)

    forest = HashForest(rt)
    forest.refresh()  # commit the baseline + warm the hash kernels
    t0 = time.perf_counter()
    for _ in range(emission_samples):
        forest.refresh()  # every var quiescent: the steady-state cost
    quiescent_cost = (time.perf_counter() - t0) / emission_samples

    hot = [0, n_replicas // 2]

    def dirty_refresh():
        for v in ids[: max(2, n_vars // 8)]:  # a few hot vars
            rt._aae_mark(v, hot)
        forest.refresh()

    dirty_refresh()  # warm the subset kernel
    dirty_s = min(_timed(dirty_refresh) for _ in range(step_samples))

    def full_rebuild():
        for v in ids:
            rt._aae_mark(v, None)
        forest.refresh()

    full_rebuild()
    full_s = min(_timed(full_rebuild) for _ in range(step_samples))
    return {
        "refresh_cost_quiescent_s": round(quiescent_cost, 9),
        "round_seconds": round(round_s, 6),
        "overhead_frac": round(
            quiescent_cost / round_s if round_s > 0 else 0.0, 4
        ),
        "dirty_refresh_seconds": round(dirty_s, 6),
        "full_rebuild_seconds": round(full_s, 6),
        "incremental_vs_full": round(
            full_s / dirty_s if dirty_s > 0 else 0.0, 2
        ),
        "n_vars": n_vars,
        "n_replicas": n_replicas,
    }


def _measure_ledger(emission_samples: int, step_s: float, round_s: float,
                    dispatches_per_round: int) -> dict:
    """Kernel-cost-ledger arm of the guard: one ``ledger.record`` per
    dispatch is the ONLY cost the roofline observatory adds to the hot
    path (its timing fences reuse syncs the dispatch already performs),
    so the guard prices the record itself — the analytic-model compute,
    the locked dict update, and its amortized share of the sampled
    gauge refresh (every ``SAMPLE_EVERY``-th record runs the
    ``gossip.ledger_sample`` span + gauge sets; the loop is long enough
    to include those ticks). A dense round books ONE store record; a
    planned frontier round books one per group dispatch."""
    prev = _registry.enabled()
    ledger = _roofline.get_ledger()
    # consume the signature's compile-bucket slot outside the clock so
    # the measured loop prices the steady-state path
    ledger.record("rows", "OverheadProbe", n_replicas=1024, fanout=3,
                  seconds=1e-6, row_bytes=64, rows=16)

    def record_pass(flag: bool) -> float:
        _registry.set_enabled(flag)
        try:
            t0 = time.perf_counter()
            for _ in range(emission_samples):
                ledger.record(
                    "rows", "OverheadProbe", n_replicas=1024, fanout=3,
                    seconds=1e-6, row_bytes=64, rows=16,
                )
            return (time.perf_counter() - t0) / emission_samples
        finally:
            _registry.set_enabled(prev)

    cost = max(0.0, record_pass(True) - record_pass(False))
    per_round = cost * max(dispatches_per_round, 1)
    return {
        "cost_per_record_s": round(cost, 9),
        "dense_overhead_frac": round(
            cost / step_s if step_s > 0 else 0.0, 4
        ),
        "frontier_overhead_frac": round(
            per_round / round_s if round_s > 0 else 0.0, 4
        ),
        "emission_samples": emission_samples,
    }


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
