"""Shared append-only JSONL sink: the ONE file writer behind the span
ring and the causal event log.

Both rings can be fed from many threads at once (the bridge's
per-connection threads, the mesh's batch-dispatch callers, a watch
callback firing under ``Store._write``); a naive per-module
open-and-write would interleave partial lines. This class owns the
whole serialize-and-write critical section under one lock — a line
either lands complete or not at all — and keeps the sink-failure
contract every telemetry surface shares: a broken file disables the
sink LOUDLY ONCE (stderr) instead of failing every traced operation
from then on.

Env-var default semantics (mirrors the original span sink): the first
append resolves the configured env var exactly once; an explicit
:meth:`configure` beats the env var, and ``configure("")`` closes and
disables the sink.
"""

from __future__ import annotations

import json
import os
import sys
import threading


class JsonlSink:
    """Thread-safe append-only JSONL file writer (one JSON object per
    line). All state transitions — env resolution, lazy open, write,
    failure-disable — happen under the instance lock."""

    def __init__(self, env_var: "str | None" = None):
        self._env_var = env_var
        self._lock = threading.Lock()
        self._path: "str | None" = None
        self._file = None
        self._checked = env_var is None  # no env var: nothing to resolve
        self.lines_written = 0

    def configure(self, path: "str | None") -> None:
        """``path=None`` keeps the current file; ``""`` closes and
        disables; anything else re-targets the sink."""
        if path is None:
            return
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
            self._file = None
            self._path = path or None
            self._checked = True  # explicit configure beats the env var

    @property
    def path(self) -> "str | None":
        with self._lock:
            return self._path

    def append(self, rec: dict) -> None:
        """Serialize + write one record as a single line; never raises
        (a broken sink must not break the traced operation)."""
        with self._lock:
            if not self._checked:
                # first record decides the env-var default exactly once
                self._path = os.environ.get(self._env_var) or None
                self._checked = True
            if self._path is None:
                return
            try:
                # default=repr absorbs unserializable VALUES; a circular
                # container still raises — that is one bad record, so it
                # is dropped loudly without disabling the sink
                line = json.dumps(rec, default=repr) + "\n"
            except (TypeError, ValueError) as exc:
                print(
                    f"lasp_tpu.telemetry: dropped unserializable record "
                    f"({exc})",
                    file=sys.stderr,
                )
                return
            try:
                if self._file is None:
                    self._file = open(self._path, "a", buffering=1)
                self._file.write(line)
                self.lines_written += 1
            except OSError as exc:
                # disable loudly ONCE rather than failing every record
                print(
                    f"lasp_tpu.telemetry: JSONL sink {self._path!r} failed "
                    f"({exc}); file logging disabled",
                    file=sys.stderr,
                )
                self._path = None
                self._file = None
