"""Roofline observatory: analytic per-kernel traffic model + cost ledger.

The perf trajectory was blind (ISSUE 6): ``roofline_GBps`` /
``roofline_frac`` were null in every BENCH artifact and nothing
attributed wall time to the kernels the plan compiler actually
dispatches. This module is the instrument:

- :func:`kernel_traffic` — the **analytic traffic model**: bytes moved
  and joins performed per dispatch for every gossip kernel family
  (dense, shift, frontier row-sparse, grouped rows/dense, fused
  windows, chaos stacked-mask, partitioned boundary exchange), derived
  from the same ``(codec, spec, R, fanout, bucket, G_active)`` tuple
  ``mesh.plan.signature_of`` keys kernels by — the JITSPMM observation
  (PAPERS.md) that cost accounting must live at the specialization
  granularity, not per run.
- :class:`KernelLedger` — the **cost ledger**: per kernel-signature
  dispatch counts, rounds, analytic bytes, joins, and wall seconds
  (fed by the runtime's dispatch sites, whose ``block_until_ready``
  syncs already close each timing window), yielding achieved GB/s and
  roofline fraction per signature against the capability registry
  (:mod:`.capability`). Sampled gauge refreshes
  (``roofline_achieved_GBps{kernel}`` / ``roofline_frac{kernel}``,
  under the ``gossip.ledger_sample`` span) keep the per-record cost a
  dict update — the overhead guard (:mod:`.overhead`) prices exactly
  this path.
- :func:`profile_capture` — the ``jax.profiler`` trace-capture hook:
  wraps any scenario callable into a Perfetto-openable trace directory.

Two byte conventions, deliberately distinct (docs/OBSERVABILITY.md):

- ``bytes_moved`` — the *ideal-traffic* roofline convention: ``(fanout
  + 2)`` row-moves per touched row (read own + gathered neighbors +
  write), the convention the bench headline has always used. This is
  what achieved GB/s divides.
- ``xla_lo`` / ``xla_hi`` — calibrated bounds on what
  ``jit(...).lower(...).compile().cost_analysis()["bytes accessed"]``
  reports for the same dispatch (operand+output buffers per post-fusion
  instruction: leafwise codecs fuse to exactly operands-once, generic
  vclock merges materialize per-column intermediates, row-sparse
  scatters pay the full-state read+write twice). The cross-check test
  (tests/telemetry/test_roofline.py) asserts ``xla_lo <= cost_analysis
  <= xla_hi`` across leafwise / vclock / packed codecs.

The ledger's lifetime follows the registry generation (like the
runtime's instrument caches): ``telemetry.reset()`` and
``registry.scratch_registry()`` detach it, so measurement harnesses
never pollute live attribution.

No jax at module scope (the telemetry import contract);
:func:`profile_capture` imports it lazily.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time

from . import registry as _registry
from .capability import device_capability
from .spans import span

#: neighbor/row index tables ride int32 on the wire
_IDX_BYTES = 4

#: every kernel family the model covers (tests pin the vocabulary).
#: ``pallas_dense`` / ``pallas_rows`` are the hand-written Mosaic
#: kernels (ops.pallas_gossip) — separate families on purpose, so the
#: roofline table shows the Pallas arm's achieved HBM fraction NEXT TO
#: the XLA arm it raced (the measure→specialize→verify loop of ISSUE 7)
FAMILIES = (
    "dense",
    "shift",
    "rows",
    "grouped_dense",
    "grouped_rows",
    "pallas_dense",
    "pallas_rows",
    "step",
    "fused_block",
    "converge",
    "chaos_window",
    "boundary_exchange",
    "shard_exchange",
    "dataflow_fused",
    "quorum_step",
    "aae_hash",
    "ingest_apply",
    "handoff_transfer",
)


@dataclasses.dataclass(frozen=True)
class TrafficEstimate:
    """One dispatch's analytic traffic: see the module docstring for
    the two byte conventions."""

    bytes_moved: int
    xla_lo: int
    xla_hi: int
    joins: int


def state_row_bytes(states, n_replicas: int) -> int:
    """Per-replica-row state footprint of a live ``[R, ...]``
    population, from leaf shape/dtype metadata only (never pulls device
    buffers — the ``rows_traffic_bytes`` discipline)."""
    import numpy as np

    total = 0
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(states)
    except Exception:
        leaves = [states]
    for leaf in leaves:
        dt = getattr(leaf, "dtype", None)
        size = getattr(leaf, "size", None)
        if dt is None or size is None:
            arr = np.asarray(leaf)
            dt, size = arr.dtype, arr.size
        total += int(size) * int(dt.itemsize)
    return total // max(int(n_replicas), 1)


def kernel_traffic(
    family: str,
    *,
    row_bytes: int,
    n_replicas: int,
    fanout: int,
    rows: "int | None" = None,
    g_active: int = 1,
    window: int = 1,
    leafwise: bool = True,
    exchange_rows: int = 0,
    n_vars: int = 1,
) -> TrafficEstimate:
    """Analytic traffic of ONE dispatch of ``family`` (see
    :data:`FAMILIES`). ``rows`` is the row-sparse bucket (pad slots
    move bytes too — they are real gather/scatter slots), ``g_active``
    the stacked group width, ``window`` the fused round count,
    ``exchange_rows`` the boundary-exchange row total for the
    partitioned family, ``n_vars`` the store width for the whole-store
    families (``step`` / ``fused_block`` / ``converge`` /
    ``chaos_window``, where ``row_bytes`` is the whole STORE's
    per-replica footprint)."""
    if family not in FAMILIES:
        raise ValueError(f"unknown kernel family {family!r} "
                         f"(expected one of {FAMILIES})")
    R, K, G, T = int(n_replicas), int(fanout), int(g_active), int(window)
    S = int(row_bytes) * R  # one member's full-population footprint
    N = R * K * _IDX_BYTES  # the neighbor table
    pad = 4096  # small-constant fusion slack (scalars, predicates)

    if family in ("dense", "shift"):
        ntab = 0 if family == "shift" else N
        moved = (K + 2) * S
        lo = 2 * S + ntab
        hi = (
            round(1.15 * (2 * S + ntab)) + pad
            if leafwise
            else (2 + K) * S + ntab + pad
        )
        return TrafficEstimate(moved, lo, hi, R * K)

    if family == "pallas_dense":
        # the hand-written streamed gather+join (ops.pallas_gossip.
        # pallas_gossip_round): K row reads + 1 own read + 1 write per
        # replica per plane, never a gathered HBM intermediate — ideal
        # traffic IS the dense convention (same numerator, so the two
        # arms' achieved GB/s compare directly), and the bounds are
        # tight around it because the kernel cannot materialize more
        moved = (K + 2) * S
        lo = 2 * S + N
        hi = (2 + K) * S + N + pad
        return TrafficEstimate(moved, lo, hi, R * K)

    if family == "pallas_rows":
        # the row-sparse gather–join–scatter kernel (ops.pallas_gossip.
        # pallas_gossip_round_rows[_grouped]): per bucket slot, (K+1)
        # leaf-row DMAs in + the joined row out of VMEM, double-buffered
        # — same ideal numerator as the XLA ``rows`` family so the race
        # compares like-for-like; the hi bound adds the donated scatter
        # epilogue's full-state read+write (outside the kernel, still
        # this dispatch's traffic)
        F = int(rows or 0)
        moved = G * ((K + 2) * F * int(row_bytes) + F * (K + 2) * _IDX_BYTES)
        lo = G * (K + 2) * F * int(row_bytes)
        hi = (
            2 * G * S + G * (2 * K + 4) * F * int(row_bytes) + N + pad
        )
        return TrafficEstimate(moved, lo, hi, G * F * K)

    if family == "rows":
        F = int(rows or 0)
        moved = (K + 2) * F * int(row_bytes) + F * (K + 2) * _IDX_BYTES
        # measured shape (CPU cost_analysis): ~4S for the scatter's full
        # read+write on top of the base read, + per-row working buffers
        lo = 2 * S
        hi = 4 * S + N + (2 * K + 4) * F * int(row_bytes) + F * 64 + pad
        return TrafficEstimate(moved, lo, hi, F * K)

    if family == "grouped_dense":
        moved = G * (K + 2) * S
        lo = 2 * G * S + N
        hi = (
            round(1.15 * (2 * G * S + N)) + pad
            if leafwise
            else (2 + K) * G * S + N + pad
        )
        return TrafficEstimate(moved, lo, hi, G * R * K)

    if family == "grouped_rows":
        F = int(rows or 0)
        moved = G * ((K + 2) * F * int(row_bytes) + F * (K + 2) * _IDX_BYTES)
        lo = 2 * G * S
        # the vmapped rows kernel pays ~1.5x the single-var rows cost
        # per member (batched gathers materialize per-member full-state
        # intermediates — measured on the CPU backend)
        hi = (
            G * (6 * S + (2 * K + 6) * F * int(row_bytes) + F * 64)
            + N + 4 * pad
        )
        return TrafficEstimate(moved, lo, hi, G * F * K)

    if family in ("step", "fused_block", "converge", "chaos_window"):
        # whole-store families: row_bytes is the STORE's per-replica
        # footprint; the mask operand of a chaos window adds R*K bools
        # per round
        per_round = (K + 2) * S
        mask = R * K if family == "chaos_window" else 0
        moved = T * (per_round + mask)
        lo = T * (2 * S)
        hi = T * ((2 + K) * S + N + mask) + pad
        return TrafficEstimate(moved, lo, hi, T * R * K * int(n_vars))

    if family == "dataflow_fused":
        # the whole-graph propagate megakernel (dataflow.plan +
        # ops.fused.fused_dataflow_rounds): ``row_bytes`` is the
        # analytic traffic of ONE Jacobi sweep over the dirty closure
        # (every closure edge reads its source states + tables, every
        # distinct dst reads + writes once through the merge chain —
        # ``dataflow.plan.sweep_traffic_bytes``), ``window`` the sweeps
        # the on-device while_loop executed, ``n_vars`` the closure's
        # edge count. The xla bounds are nominal here: a while_loop's
        # ``cost_analysis`` is trip-count-blind, so no calibrated
        # cross-check exists for this family (unlike dense/rows/
        # grouped); the hi bound covers per-dst merge intermediates.
        moved = T * int(row_bytes)
        lo = T * int(row_bytes)
        hi = 3 * T * int(row_bytes) + pad
        return TrafficEstimate(moved, lo, hi, T * int(n_vars))

    if family == "quorum_step":
        # the quorum FSM transition kernel (quorum.fsm.transition_
        # batched): pure CONTROL-PLANE traffic — per request the
        # struct-of-arrays slices (state/coord/deadline/need ~16B) plus
        # the pick/ack/reach lanes (K slots × ~6B: int32 pick + bools),
        # plus the shared component labeling + liveness planes (R × 5B)
        # read once per dispatch. ``rows`` is the padded request bucket,
        # ``fanout`` the preflist width N. Deliberately tiny next to the
        # state-moving families — the point of the ledger row is showing
        # that coordination control costs ~nothing next to the joins it
        # schedules. No calibrated xla bounds (the kernel is a handful
        # of elementwise ops; cost_analysis noise dominates).
        F = int(rows or 0)
        moved = F * (16 + 6 * K) + R * 5
        lo = F * (8 + 4 * K)
        hi = 4 * moved + pad
        return TrafficEstimate(moved, lo, hi, F * K)

    if family == "aae_hash":
        # the AAE row-hash kernel (aae.hashtree): per hashed row one
        # full state-row read plus a 4-byte hash out, stacked G-wide
        # for plan-group dispatches; ``rows`` is the rows hashed
        # (bucket-padded subsets move their pad slots too). The hi
        # bound covers the uint32 word-view materialization the XLA
        # lowering may pay on bool planes. No joins — hashing reads,
        # never merges.
        F = int(rows or 0)
        moved = G * F * (int(row_bytes) + 4)
        lo = G * F * int(row_bytes)
        hi = 3 * G * F * (int(row_bytes) + 4) + pad
        return TrafficEstimate(moved, lo, hi, 0)

    if family == "ingest_apply":
        # the grouped client-op apply kernel (mesh.ingest): per table
        # slot the scatter indices/payload stream in (~4 int32-ish
        # columns) and the targeted state entries read+write — bounded
        # above by one full row per slot (an OR-Set tombstone rewrites
        # a [T] token row; a counter bump touches one lane), plus the
        # [G, R] changed-flag plane out. Coarse by design, like
        # ``quorum_step``: the ledger row exists to show ingest's
        # device cost next to the gossip rounds it feeds, not to chase
        # an HBM bound (the kernel is scatter-latency-, not
        # bandwidth-, limited). No calibrated xla bounds.
        F = int(rows or 0)
        moved = G * F * (4 * _IDX_BYTES + 2 * int(row_bytes)) + G * R
        lo = G * F * 4 * _IDX_BYTES
        hi = 4 * moved + 2 * G * S + pad
        return TrafficEstimate(moved, lo, hi, G * F)

    if family == "handoff_transfer":
        # the grouped ownership-transfer join (membership.handoff.
        # grouped_transfer): per bucket-padded transfer pair one
        # source-row gather, one target-row gather, and the merged
        # target-row scatter, stacked G-wide across the dispatch-plan
        # group (pad slots gather real bytes and DROP at the scatter —
        # the out-of-range pad contract). Coarse like quorum_step /
        # ingest_apply: the row exists to show rebalancing's device
        # cost next to the gossip it interleaves with, not to chase an
        # HBM bound. ``rows`` is the pair bucket.
        F = int(rows or 0)
        moved = G * F * 3 * int(row_bytes)
        lo = G * F * 2 * int(row_bytes)
        hi = 4 * moved + pad
        return TrafficEstimate(moved, lo, hi, G * F)

    if family == "shard_exchange":
        # the SPARSE partitioned frontier round (shard_gossip.
        # partitioned_frontier_round_fn): ``rows`` is the bucket-padded
        # cut-row payload the collective moves (crossing the wire twice,
        # send + receive; pad slots are real collective slots),
        # ``exchange_rows`` the frontier-reachable rows the overlapped
        # interior/boundary joins touch — (K+1) gathered rows + 1
        # written per touched row, stacked G-wide. The runtime's
        # dispatch site passes exact figures (bytes_moved/joins
        # overrides); this analytic branch seeds the family for
        # roofline_workload and prices ad-hoc calls.
        X = int(rows or 0)  # payload rows (bucket-padded)
        F = int(exchange_rows)  # joined (touched) rows
        moved = G * (2 * X + (K + 2) * F) * int(row_bytes)
        lo = G * 2 * X * int(row_bytes)
        hi = (
            G * (2 * X + (2 * K + 4) * F) * int(row_bytes)
            + 2 * G * S + N + pad
        )
        return TrafficEstimate(moved, lo, hi, G * F * K)

    # boundary_exchange: the partitioned round's wire+local traffic —
    # local read+write of the population plus the cut rows crossing the
    # collective twice (send + receive)
    moved = 2 * S + 2 * int(exchange_rows) * int(row_bytes) + N
    lo = 2 * S
    hi = (2 + K) * S + N + 2 * int(exchange_rows) * int(row_bytes) + pad
    return TrafficEstimate(moved, lo, hi, R * K)


def cost_analysis_bytes(compiled) -> "float | None":
    """``bytes accessed`` from a compiled executable's cost analysis,
    or None where the backend provides none (the cross-check is
    best-effort by contract)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    v = ca.get("bytes accessed")
    return float(v) if v is not None else None


# ---------------------------------------------------------------------------
# the kernel cost ledger
# ---------------------------------------------------------------------------


class KernelLedger:
    """Per-kernel-signature cost attribution: dispatches, rounds,
    analytic bytes, joins, wall seconds -> achieved GB/s and roofline
    fraction. ``record`` is the hot path (a dict update under one
    lock); every ``SAMPLE_EVERY``-th dispatch of a signature refreshes
    that signature's gauges under the ``gossip.ledger_sample`` span."""

    #: gauge refresh cadence per signature (the first dispatch always
    #: samples, so short runs still export)
    SAMPLE_EVERY = 16

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels: dict = {}
        self._totals = {
            "dispatches": 0, "rounds": 0, "bytes": 0, "joins": 0,
            "seconds": 0.0, "compile_seconds": 0.0,
        }

    @staticmethod
    def _label(family, codec_name, n_replicas, fanout, rows, g_active):
        lab = f"{family}:{codec_name}:R{n_replicas}k{fanout}"
        if rows:
            lab += f":b{rows}"
        if g_active > 1:
            lab += f":G{g_active}"
        return lab

    def record(
        self,
        family: str,
        codec_name: str,
        *,
        n_replicas: int,
        fanout: int,
        seconds: float,
        row_bytes: int = 0,
        rows: "int | None" = None,
        g_active: int = 1,
        window: int = 1,
        leafwise: bool = True,
        bytes_moved: "int | None" = None,
        joins: "int | None" = None,
        rounds: "int | None" = None,
        n_vars: int = 1,
    ) -> None:
        """Attribute one dispatch. ``bytes_moved``/``joins`` override
        the analytic model where the caller already holds the exact
        figure (the whole-store step's ``round_traffic_bytes``)."""
        if not _registry.enabled():
            return
        if bytes_moved is None or joins is None:
            est = kernel_traffic(
                family, row_bytes=row_bytes, n_replicas=n_replicas,
                fanout=fanout, rows=rows, g_active=g_active, window=window,
                leafwise=leafwise, n_vars=n_vars,
            )
            if bytes_moved is None:
                bytes_moved = est.bytes_moved
            if joins is None:
                joins = est.joins
        rounds = int(window if rounds is None else rounds)
        label = self._label(
            family, codec_name, int(n_replicas), int(fanout),
            int(rows or 0), int(g_active),
        )
        with self._lock:
            ent = self._kernels.get(label)
            if ent is None:
                ent = self._kernels[label] = {
                    "kernel": label,
                    "family": family,
                    "codec": codec_name,
                    "n_replicas": int(n_replicas),
                    "fanout": int(fanout),
                    "bucket": int(rows or 0),
                    "g_active": int(g_active),
                    "dispatches": 0,
                    "rounds": 0,
                    "bytes": 0,
                    "joins": 0,
                    "seconds": 0.0,
                    "compile_dispatches": 0,
                    "compile_seconds": 0.0,
                }
            if ent["dispatches"] == 0 and ent["compile_dispatches"] == 0:
                # a signature's FIRST dispatch carries trace+compile
                # time: bank it separately so achieved GB/s reflects
                # warm dispatches only (the roofline question), never a
                # one-off XLA compile
                ent["compile_dispatches"] += 1
                ent["compile_seconds"] += float(seconds)
                self._totals["compile_seconds"] = (
                    self._totals.get("compile_seconds", 0.0) + float(seconds)
                )
                return
            ent["dispatches"] += 1
            ent["rounds"] += rounds
            ent["bytes"] += int(bytes_moved)
            ent["joins"] += int(joins)
            ent["seconds"] += float(seconds)
            tot = self._totals
            tot["dispatches"] += 1
            tot["rounds"] += rounds
            tot["bytes"] += int(bytes_moved)
            tot["joins"] += int(joins)
            tot["seconds"] += float(seconds)
            do_sample = ent["dispatches"] % self.SAMPLE_EVERY == 1
            if do_sample:
                sample = dict(ent)
        if do_sample:
            self._sample(sample)

    @staticmethod
    def _rates(ent) -> "tuple[float | None, float | None]":
        secs = ent["seconds"]
        if secs <= 0:
            return None, None
        gbps = ent["bytes"] / secs / 1e9
        peak = device_capability().get("peak_GBps")
        frac = (gbps / peak) if peak else None
        return round(gbps, 3), (round(frac, 4) if frac is not None else None)

    def _sample(self, ent) -> None:
        """One sampled gauge refresh for a signature (the throttled
        export path — the per-record cost must never include a registry
        walk per dispatch). Uses the NON-BLOCKING cached peak: the
        one-shot host-bandwidth probe belongs to read surfaces (CLI /
        bench / health), never a dispatch path."""
        from .capability import cached_peak_gbps

        with span("gossip.ledger_sample", kernel=ent["kernel"]):
            secs = ent["seconds"]
            if secs <= 0:
                return
            gbps = round(ent["bytes"] / secs / 1e9, 3)
            _registry.gauge(
                "roofline_achieved_GBps",
                help="achieved GB/s per kernel signature (analytic "
                     "bytes over ledger-attributed wall time)",
                kernel=ent["kernel"],
            ).set(gbps)
            peak = cached_peak_gbps()
            if peak:
                _registry.gauge(
                    "roofline_frac",
                    help="achieved GB/s over the capability registry's "
                         "roofline denominator, per kernel signature",
                    kernel=ent["kernel"],
                ).set(round(gbps / peak, 4))

    def totals(self) -> dict:
        """Whole-ledger accumulators (bench arms diff this around a
        measured region to attribute bytes to the region)."""
        with self._lock:
            return dict(self._totals)

    def snapshot(self) -> list:
        """Per-signature table (most wall time first), each row carrying
        achieved GB/s + roofline fraction against the current
        capability."""
        with self._lock:
            rows = [dict(e) for e in self._kernels.values()]
        for ent in rows:
            gbps, frac = self._rates(ent)
            ent["achieved_GBps"] = gbps
            ent["roofline_frac"] = frac
        rows.sort(key=lambda e: -e["seconds"])
        return rows

    def summary(self, top: int = 8) -> dict:
        """The health-view condensation (``ConvergenceMonitor.health()
        ["roofline"]``)."""
        rows = self.snapshot()
        tot = self.totals()
        gbps = (
            round(tot["bytes"] / tot["seconds"] / 1e9, 3)
            if tot["seconds"] > 0 else None
        )
        cap = device_capability() if rows else None
        peak = cap.get("peak_GBps") if cap else None
        return {
            "kernels": [
                {
                    k: ent[k]
                    for k in ("kernel", "family", "dispatches", "rounds",
                              "bytes", "seconds", "achieved_GBps",
                              "roofline_frac")
                }
                for ent in rows[:top]
            ],
            "totals": tot,
            "achieved_GBps": gbps,
            "peak_GBps": peak,
            "roofline_frac": (
                round(gbps / peak, 4) if gbps and peak else None
            ),
        }


_ledger: "KernelLedger | None" = None
_ledger_gen: "int | None" = None
_ledger_lock = threading.Lock()


def get_ledger() -> KernelLedger:
    """The process-global ledger. Its lifetime follows the registry
    generation: ``telemetry.reset()`` / ``scratch_registry()`` detach
    it (a fresh ledger appears), so measurement harnesses never bleed
    synthetic dispatches into live attribution. Creation is locked: a
    stepping thread and a health-scrape thread racing the first access
    after a generation bump must agree on ONE instance, or one side's
    records would silently vanish."""
    global _ledger, _ledger_gen
    gen = _registry.generation()
    led = _ledger
    if led is not None and _ledger_gen == gen:
        return led
    with _ledger_lock:
        if _ledger is None or _ledger_gen != gen:
            _ledger = KernelLedger()
            _ledger_gen = gen
        return _ledger


# ---------------------------------------------------------------------------
# profiler capture hook
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def profile_capture(log_dir: str = "./profile_capture"):
    """Wrap a region in a ``jax.profiler`` trace — the whole-scenario
    capture hook (`with profile_capture("/tmp/t"): scenario()`), open
    the resulting directory in Perfetto / TensorBoard. Yields the
    trace directory. Requires jax (imported lazily — using the hook IS
    opting into a backend)."""
    import jax

    with jax.profiler.trace(str(log_dir)):
        yield str(log_dir)


def capture_scenario(fn, log_dir: str = "./profile_capture", **kwargs):
    """Run ``fn(**kwargs)`` under :func:`profile_capture`; returns
    ``(result, trace_dir)`` — the one-call form for scenario
    callables (``capture_scenario(frontier_sparse)``)."""
    t0 = time.perf_counter()
    with profile_capture(log_dir) as d:
        out = fn(**kwargs)
    _registry.histogram(
        "profile_capture_seconds",
        help="wall time of whole-scenario jax.profiler captures",
    ).observe(time.perf_counter() - t0)
    return out, d
