"""Device capability registry + hardened probe-report schema.

Two jobs, both born from the perf trajectory going blind (ISSUE 6):

1. **Capability registry** — the one table of peak HBM bandwidth by
   device kind (previously duplicated as private ``_ROOFLINE_GBPS``
   tuples in ``bench.py`` and ``tools/tpu_oneshot.py``, and hardcoded
   prose in docs/PERF.md), plus a one-shot **measured host-memory
   bandwidth probe** so CPU-fallback runs get a real roofline
   denominator instead of ``null``. ``device_capability()`` is the
   single lookup every consumer (bench child, oneshot capture, kernel
   cost ledger, ``lasp_tpu roofline``) reads.

2. **Probe-report schema** — r03–r05 fell back to CPU because the TPU
   probe failed *and the only stderr line surfaced was the harmless
   experimental-platform WARNING*; the actual fatal error was
   discarded. :func:`classify_probe_attempt` separates warning noise
   from the fatal line and classifies the failure (import error / init
   timeout / signal / no devices / cpu only), and
   :func:`build_probe_report` assembles the structured record every
   BENCH artifact now carries. The key sets (:data:`PROBE_REPORT_KEYS`,
   :data:`PROBE_ATTEMPT_KEYS`) are an interface: the "Probe report
   schema" table in docs/OBSERVABILITY.md is linted against them both
   ways by ``tools/check_metrics_catalog.py``.

This module must stay importable WITHOUT jax (the bench parent and the
capture watcher never initialize a backend — the single-client axon
tunnel wedges on concurrent connects). Device identity is read only
when jax is ALREADY imported, the same rule ``spans.annotate`` uses.
"""

from __future__ import annotations

import re as _re
import sys
import time

from . import registry as _registry

#: single-chip peak HBM bandwidth, GB/s, by device-kind substring —
#: first match wins, so more specific kinds sort first. v5e was pinned
#: in docs/PERF.md prose before this registry existed.
PEAK_HBM_GBPS = (
    ("v6e", 1638.0),
    ("v6", 1638.0),
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def peak_gbps_for_kind(device_kind: str) -> "float | None":
    """Pinned peak HBM GB/s for a device-kind string, or None when the
    kind is not in the registry (an unknown accelerator must report an
    honest null, never a guessed denominator)."""
    k = str(device_kind).lower()
    for sub, gbps in PEAK_HBM_GBPS:
        if sub in k:
            return gbps
    return None


_host_bw: dict = {}


def measure_host_bandwidth(size_mb: int = 128, reps: int = 3) -> float:
    """One-shot measured host-memory bandwidth, GB/s (cached per
    ``(size_mb, reps)`` for the process lifetime — a small-buffer probe
    from a test must never fix the roofline denominator for everyone
    else): best-of-``reps`` large ``np.copyto`` passes, the read+write
    traffic convention (2 bytes moved per byte copied). ~100 ms once;
    never called by lightweight parents (only consumers that actually
    need a denominator)."""
    key = (int(size_mb), int(reps))
    cached = _host_bw.get(key)
    if cached is not None:
        return cached
    import numpy as np

    n = max(1, int(size_mb)) * (1 << 20) // 8
    src = np.ones(n, dtype=np.float64)
    dst = np.empty_like(src)
    best = float("inf")
    for i in range(reps + 1):
        t0 = time.perf_counter()
        np.copyto(dst, src)
        dt = time.perf_counter() - t0
        if i:  # first pass warms the pages, not the clock
            best = min(best, dt)
    _host_bw[key] = round(2 * n * 8 / best / 1e9, 2)
    return _host_bw[key]


_capability: "dict | None" = None
#: whether the cached record was resolved with jax importable — a
#: pre-jax call must NOT pin the measured-host denominator for a
#: process that later initializes an accelerator backend
_capability_saw_jax = False
#: registry generation the capability gauge was last emitted into —
#: like the ledger's lifetime rule, ``telemetry.reset()`` /
#: ``scratch_registry()`` wipe the gauge, so a cache HIT must re-emit
#: into the new generation or the denominator vanishes from exports
#: while roofline_frac gauges keep appearing
_capability_gauge_gen: "int | None" = None


def _emit_capability_gauge(cap: dict) -> None:
    global _capability_gauge_gen
    _registry.gauge(
        "capability_peak_GBps",
        help="roofline denominator: peak HBM GB/s (pinned by device "
             "kind) or measured host-memory bandwidth on CPU",
        device_kind=cap["device_kind"],
        source=cap["source"],
    ).set(cap["peak_GBps"] if cap["peak_GBps"] is not None else 0)
    _capability_gauge_gen = _registry.generation()


def cached_peak_gbps() -> "float | None":
    """The cached capability's roofline denominator WITHOUT triggering
    the one-shot host probe — the hot-path accessor (the kernel cost
    ledger's sampled gauge refresh must never pay a ~100 ms bandwidth
    measurement inside a dispatch path). None until some read surface
    (CLI, bench, health, smoke) has resolved :func:`device_capability`
    — and None again for a record cached before jax appeared (same
    staleness rule as :func:`device_capability`: a pre-jax measured-host
    number must never serve as an accelerator run's denominator; the
    gauges stay unset until a read surface re-resolves)."""
    if _capability is None:
        return None
    if not _capability_saw_jax and "jax" in sys.modules:
        return None
    return _capability["peak_GBps"]


def device_capability(refresh: bool = False) -> dict:
    """The attached accelerator's capability record (cached):
    ``{"platform", "device_kind", "peak_GBps", "source"}`` where source
    is ``"pinned"`` (registry hit), ``"measured-host"`` (the CPU
    probe), or ``"unknown"`` (an accelerator kind the registry does not
    know — ``peak_GBps`` stays None rather than lying). Reads jax only
    when it is already imported; a jax-free process reports the
    measured host capability — but a record cached BEFORE jax appeared
    re-resolves on the first call after import, so an early startup
    call can never pin host-DRAM bandwidth as a TPU run's denominator."""
    global _capability, _capability_saw_jax
    jax_present = "jax" in sys.modules
    if (_capability is not None and not refresh
            and (_capability_saw_jax or not jax_present)):
        if _capability_gauge_gen != _registry.generation():
            _emit_capability_gauge(_capability)
        return _capability
    platform, kind = "cpu", "cpu"
    if jax_present:
        import jax

        try:
            d = jax.devices()[0]
            platform = str(d.platform)
            kind = str(getattr(d, "device_kind", d.platform))
        except Exception:
            pass  # backend init failure: fall through to the host view
    peak: "float | None" = None
    source = "unknown"
    if platform != "cpu":
        peak = peak_gbps_for_kind(kind)
        source = "pinned" if peak is not None else "unknown"
    else:
        peak = measure_host_bandwidth()
        source = "measured-host"
    cap = {
        "platform": platform,
        "device_kind": kind,
        "peak_GBps": peak,
        "source": source,
    }
    _emit_capability_gauge(cap)
    _capability = cap
    _capability_saw_jax = jax_present
    return cap


# ---------------------------------------------------------------------------
# probe-report schema (the hardened TPU capture path)
# ---------------------------------------------------------------------------

#: top-level keys of a probe report — linted both ways against the
#: "Probe report schema" table in docs/OBSERVABILITY.md
PROBE_REPORT_KEYS = (
    "ok",
    "platforms_seen",
    "attempts",
    "reason",
    "elapsed_s",
)

#: per-attempt keys inside ``probe_report["attempts"]``
PROBE_ATTEMPT_KEYS = (
    "attempt",
    "rc",
    "classification",
    "fatal",
    "warnings",
    "stderr_tail",
    "seconds",
)

#: the bounded-subprocess timeout sentinel. NOT -1: subprocess reports
#: a child killed by signal N as returncode -N, so -1 is SIGHUP and a
#: sentinel colliding with it would classify a hangup as init_timeout.
#: No POSIX signal can produce -257. bench.py's ``_run`` returns this
#: (a drift test pins the two constants together).
PROBE_TIMEOUT_RC = -257

#: the closed classification vocabulary (tests pin it)
PROBE_CLASSIFICATIONS = (
    "ok",
    "cpu_only",
    "init_timeout",
    "signal",
    "import_error",
    "no_devices",
    "nonzero_exit",
    "no_probe_output",
    "budget_exceeded",
)

#: warning-tier line shapes, ANCHORED to where the emitters put them:
#: logging-module records lead with the level ("WARNING:..."), and the
#: warnings module formats "path.py:123: SomeWarning: ...". A fatal
#: line that merely MENTIONS a warning ("RuntimeError: ... see WARNING
#: above") must stay in the fatal tier — a substring match would demote
#: it to noise and null the verdict, the exact r03–r05 blind spot.
#: Deliberately NO bare "XWarning:" alternative: that shape only
#: appears as the final line of a RAISED warning (PYTHONWARNINGS=error)
#: — i.e. precisely when it IS the verdict.
_WARNING_LINE = _re.compile(
    r"^WARNING\b"               # logging-module level prefix
    r"|:\d+:\s+\w*Warning:"     # warnings.warn "file.py:123: XWarning:"
)


def _split_stderr(stderr: str) -> "tuple[list, str | None]":
    """(warning lines, fatal line): warnings are the known-noise tier
    (the experimental-platform WARNING that used to masquerade as the
    failure cause); the fatal line is the LAST non-empty non-warning
    line — where Python tracebacks and backend errors put the verdict."""
    warnings: list = []
    fatal: "str | None" = None
    for line in (stderr or "").splitlines():
        line = line.strip()
        if not line:
            continue
        if _WARNING_LINE.search(line):
            warnings.append(line)
        else:
            fatal = line
    return warnings, fatal


def classify_probe_attempt(rc: int, stdout: str, stderr: str,
                           timeout_rc: int = PROBE_TIMEOUT_RC,
                           budget_exceeded: bool = False,
                           ) -> "tuple[dict, list]":
    """Classify one bounded-subprocess probe attempt. Returns
    ``(attempt_record, platforms)`` where the record carries every
    :data:`PROBE_ATTEMPT_KEYS` member except ``attempt``/``seconds``
    (the caller stamps those) and ``platforms`` lists the backend
    platforms the probe actually saw (``PLATFORMS=...`` on stdout).
    ``budget_exceeded`` is for a WATCHER that killed a healthy-but-slow
    child itself: without it the watcher's own SIGTERM would classify
    as ``signal`` and the record would read like an external kill."""
    warnings, fatal = _split_stderr(stderr)
    platforms: list = []
    for line in (stdout or "").splitlines():
        if "PLATFORMS=" in line:
            platforms = [
                p for p in line.rsplit("PLATFORMS=", 1)[1].strip().split(",")
                if p
            ]
        elif "PLATFORM=" in line:  # the legacy single-platform probe
            platforms = [line.rsplit("PLATFORM=", 1)[1].strip()]
    if budget_exceeded:
        cls = "budget_exceeded"
    elif rc == 0 and platforms:
        cls = "ok" if any(p != "cpu" for p in platforms) else "cpu_only"
    elif rc == 0:
        # clean exit with no platform evidence (e.g. the capture
        # watcher classifies a child whose stdout it never saw): a
        # "nonzero_exit" label here would contradict rc=0
        cls = "no_probe_output"
    elif rc == timeout_rc:
        cls = "init_timeout"
    elif rc < 0:
        cls = "signal"
    elif any(
        m in (stderr or "")
        for m in ("ModuleNotFoundError", "ImportError")
    ):
        cls = "import_error"
    elif any(
        m in (stderr or "")
        for m in ("No visible device", "no devices", "Unable to initialize "
                  "backend", "FAILED_PRECONDITION")
    ):
        cls = "no_devices"
    else:
        cls = "nonzero_exit"
    record = {
        "rc": int(rc),
        "classification": cls,
        "fatal": fatal,
        "warnings": warnings,
        "stderr_tail": (stderr or "")[-2000:],
    }
    return record, platforms


def build_probe_report(attempts: list, platforms_seen, ok: bool,
                       reason: "str | None",
                       elapsed_s: float) -> dict:
    """Assemble the structured probe report (:data:`PROBE_REPORT_KEYS`)
    that replaces the swallowed stderr tail in BENCH artifacts."""
    return {
        "ok": bool(ok),
        "platforms_seen": sorted(set(platforms_seen)),
        "attempts": list(attempts),
        "reason": reason,
        "elapsed_s": round(float(elapsed_s), 1),
    }
