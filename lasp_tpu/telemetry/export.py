"""Export surfaces: Prometheus text exposition + JSONL dumps.

Two consumers, two formats, one registry snapshot:

- :func:`render_prometheus` — the text exposition format (version 0.0.4)
  a Prometheus server scrapes. The bridge serves it on the ``metrics``
  protocol verb (so a BEAM node — or anything that can speak the frame
  protocol — can scrape), and ``lasp_tpu metrics`` prints it.
- :func:`dump_jsonl` — one JSON object per line: every span event in the
  ring, then one ``{"kind": "metric", ...}`` line per series. This is
  the offline-analysis surface (``lasp_tpu metrics --jsonl``).

Rendering is deterministic (names and label sets sorted), which is what
makes the golden-file test (tests/telemetry/test_prometheus.py) and
diff-based dashboards possible.
"""

from __future__ import annotations

import json

from . import registry as _registry
from . import spans as _spans


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: dict, extra: "tuple | None" = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items = items + [extra]
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape(str(v))}"' for k, v in items)
    return "{" + body + "}"


def render_prometheus(snapshot: "dict | None" = None) -> str:
    """Prometheus text exposition of ``snapshot`` (default: a fresh
    snapshot of the process-global registry)."""
    snap = _registry.get_registry().snapshot() if snapshot is None else snapshot
    lines: list[str] = []
    for name in sorted(snap):
        fam = snap[name]
        if fam["help"]:
            lines.append(f"# HELP {name} {fam['help']}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for entry in sorted(
            fam["series"], key=lambda e: sorted(e["labels"].items())
        ):
            labels = entry["labels"]
            if fam["type"] == "histogram":
                acc = 0
                bounds = list(entry["buckets"]) + [float("inf")]
                for b, c in zip(bounds, entry["counts"]):
                    acc += c
                    le = "+Inf" if b == float("inf") else _fmt_value(b)
                    lines.append(
                        f"{name}_bucket{_label_str(labels, ('le', le))} {acc}"
                    )
                lines.append(
                    f"{name}_sum{_label_str(labels)} "
                    f"{_fmt_value(entry['sum'])}"
                )
                lines.append(
                    f"{name}_count{_label_str(labels)} {entry['count']}"
                )
            else:
                lines.append(
                    f"{name}{_label_str(labels)} {_fmt_value(entry['value'])}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


def metric_events(snapshot: "dict | None" = None) -> list:
    """The snapshot as flat JSONL-able event dicts (one per series)."""
    snap = _registry.get_registry().snapshot() if snapshot is None else snapshot
    out = []
    for name in sorted(snap):
        fam = snap[name]
        for entry in sorted(
            fam["series"], key=lambda e: sorted(e["labels"].items())
        ):
            rec = {
                "kind": "metric",
                "name": name,
                "type": fam["type"],
                "labels": entry["labels"],
            }
            if fam["type"] == "histogram":
                rec["sum"] = entry["sum"]
                rec["count"] = entry["count"]
                rec["buckets"] = entry["buckets"]
                rec["counts"] = entry["counts"]
            else:
                rec["value"] = entry["value"]
            out.append(rec)
    return out


def dump_jsonl(fp, snapshot: "dict | None" = None) -> int:
    """Write the span ring then every metric series to ``fp`` as JSONL;
    returns the number of lines written."""
    n = 0
    for rec in _spans.events():
        fp.write(json.dumps(rec) + "\n")
        n += 1
    for rec in metric_events(snapshot):
        fp.write(json.dumps(rec) + "\n")
        n += 1
    return n
