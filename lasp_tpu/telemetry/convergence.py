"""ConvergenceMonitor: live per-variable / per-replica / per-shard
convergence state over the gossip residual stream.

The gossip step already computes a per-variable residual vector (how
many replica rows each round changed, ``mesh/runtime.py`` — the PR-1
telemetry feed); this module turns that stream plus on-demand
population probes into the operator surface the ``{health}`` bridge
verb, ``lasp_tpu top`` and the bench artifact's convergence summary
read:

- **per-var residual + staleness** — ``staleness[var]`` counts rounds
  since the variable's state last changed anywhere (rounds since
  inflation). While a variable is DIVERGED (some replica behind the
  global join) growing staleness means the mesh is stuck, not done —
  the ``stuck`` alert combines the two;
- **divergence top-K** — the variables changing at the most replicas
  last round (where to look first);
- **quiescence ETA** — geometric extrapolation of the total-residual
  decay (pull gossip on a fixed topology contracts the diverged set
  roughly geometrically; the ETA is a hint, not a promise);
- **per-replica / per-shard lag** — :meth:`probe` compares every
  replica row against the global join per variable (one device
  reduction per variable, O(population) device work but zero per-round
  cost — strictly an on-demand surface) and aggregates worst/mean lag
  per shard under a block sharding;
- **pluggable alerts** — threshold config (max staleness while
  diverged, max replica lag, residual floor) plus arbitrary predicate
  callbacks over the snapshot.

Hot-path contract (PR 1): :meth:`observe_round` is called once per step
dispatch from ``ReplicatedRuntime._emit_step_telemetry`` — dict updates
plus cached gauge writes, no device work, covered by the overhead guard
(``telemetry/overhead.py``). The module never imports jax at module
scope; :meth:`probe` pulls it lazily (CLI --help must stay light).
"""

from __future__ import annotations

import math
import threading

from . import registry as _registry
from . import events as _events

#: default alert thresholds (all overridable per monitor)
DEFAULT_THRESHOLDS = {
    # a diverged variable whose state stopped changing for this many
    # rounds is STUCK (divergence can no longer drain by gossip alone)
    "max_stale_rounds": 16,
    # worst per-replica lag (variables a replica is behind on) before
    # the replica is flagged lagging
    "max_replica_lag": None,
    # residual persisting at/above this fraction of the population for
    # max_stale_rounds flags a thrashing (non-contracting) mesh
    "max_residual_frac": None,
}


class ConvergenceMonitor:
    """Aggregates the per-round residual stream; see the module doc."""

    def __init__(self, history: int = 512, thresholds: "dict | None" = None,
                 top_k: int = 8):
        self._lock = threading.Lock()
        self.history = int(history)
        self.top_k = int(top_k)
        self.thresholds = dict(DEFAULT_THRESHOLDS)
        if thresholds:
            unknown = set(thresholds) - set(DEFAULT_THRESHOLDS)
            if unknown:
                raise TypeError(
                    f"unknown alert thresholds {sorted(unknown)} "
                    f"(known: {sorted(DEFAULT_THRESHOLDS)})"
                )
            self.thresholds.update(thresholds)
        self._alert_fns: list = []  # (name, fn(snapshot) -> bool)
        self._gen = _registry.generation()
        self._reset_state()

    def _reset_state(self) -> None:
        self.round = 0
        self.n_replicas = 0
        #: var -> {"residual", "last_change_round", "total_changes"}
        self.vars: dict = {}
        #: bounded total-residual history [(round, total), ...]
        self.residual_curve: list = []
        self.memberships: list = []  # [(round, kind, old_n, new_n)]
        self.last_probe: "dict | None" = None
        #: var -> dirty-replica frontier size after the last frontier
        #: round (delta-gossip scheduling; empty when dense-only)
        self.frontier: dict = {}
        #: latest chaos soak report (rounds_to_heal, degraded reads,
        #: repair bytes — chaos.ChaosRuntime.soak); empty outside soaks
        self.chaos: dict = {}
        #: latest quorum coordination report (latency percentiles,
        #: repair/push traffic, hint-log state — quorum.QuorumRuntime.
        #: report); empty until a quorum engine runs
        self.quorum: dict = {}
        #: latest serving front-end report (offered/completed/shed
        #: counts, parked watches, degradation-ladder level —
        #: serve.ServeFrontend.report); empty until a front-end reports
        self.serve: dict = {}
        #: latest active-anti-entropy report (detections, incidents,
        #: repair traffic, hash work — aae.AAEScrubber.report); empty
        #: until a scrubber reports
        self.aae: dict = {}
        #: CUMULATIVE grouped-ingest accounting (ops, dispatches,
        #: grouped vs fallback vars, bucket-pad waste — fed per
        #: ``ReplicatedRuntime.ingest_cycle``); empty until a cycle runs
        self.ingest: dict = {}
        self._tel: "dict | None" = None

    def _check_generation(self) -> None:
        """A test-time ``telemetry.reset()`` must detach cached gauges
        AND drop state accumulated against the old registry — the same
        generation discipline as the runtime's instrument cache."""
        gen = _registry.generation()
        if gen != self._gen:
            self._gen = gen
            self._reset_state()

    # -- the hot feed --------------------------------------------------------
    def observe_round(self, var_ids, residuals, seconds: float = 0.0,
                      n_replicas: "int | None" = None) -> None:
        """One executed gossip round: ``residuals[i]`` replicas changed
        ``var_ids[i]``. Called from the step's telemetry emission."""
        with self._lock:
            self._check_generation()
            self.round += 1
            if n_replicas:
                self.n_replicas = int(n_replicas)
            total = 0
            rnd = self.round
            vars_ = self.vars
            for v, r in zip(var_ids, residuals):
                r = int(r)
                total += r
                ent = vars_.get(v)
                if ent is None:
                    ent = vars_[v] = {
                        "residual": 0, "last_change_round": 0,
                        "total_changes": 0,
                    }
                if r:
                    ent["residual"] = r
                    ent["last_change_round"] = rnd
                    ent["total_changes"] += r
                elif ent["residual"]:
                    # steady state (most vars quiescent most rounds): a
                    # 0 -> 0 transition writes nothing — the hot-feed
                    # cost then scales with CHANGED vars, not vars
                    ent["residual"] = 0
            self.residual_curve.append((self.round, total))
            del self.residual_curve[: -self.history]
            # amortized gauge sweep: per-var staleness is a SAMPLED
            # surface (scrapes are seconds apart, rounds are ms apart),
            # so sweeping every var's gauge every round pays O(vars)
            # for values no scrape will ever see. Sweep when the var
            # census changes (fresh series must exist), at quiescence
            # (the moment exact staleness matters), and every 8th round
            # otherwise — gauges are then at most 8 rounds stale, the
            # monitor's own dict state (snapshot/health) stays exact.
            if (
                total == 0
                or self.round % 8 == 0
                or self._tel is None
                or self._tel["vars"] != tuple(self.vars)
            ):
                self._set_gauges()

    def observe_opaque_rounds(self, n: int,
                              quiescent: "bool | None" = None) -> None:
        """Advance the round clock for fused blocks / on-device while
        loops, whose per-round residual vectors never reach the host.
        ``quiescent=True`` records a terminal zero-residual point (the
        run reached its fixed point inside the dispatch)."""
        if n <= 0:
            return
        with self._lock:
            self._check_generation()
            self.round += int(n)
            if quiescent is not None:
                self.residual_curve.append(
                    (self.round, 0 if quiescent else -1)
                )
                del self.residual_curve[: -self.history]
                if quiescent:
                    for ent in self.vars.values():
                        ent["residual"] = 0

    def observe_frontier(self, var_ids, sizes) -> None:
        """Dirty-set sizes after a frontier-scheduled round — the
        delta-gossip twin of the residual feed: residual says how many
        rows CHANGED, the frontier says how many can still change."""
        with self._lock:
            self._check_generation()
            frontier = self.frontier
            for v, n in zip(var_ids, sizes):
                n = int(n)
                if frontier.get(v) != n:  # skip the quiescent majority
                    frontier[v] = n

    def observe_chaos(self, **report) -> None:
        """Fold a chaos soak's outcome into the health surface — the
        resilience twin of the residual feed: ``rounds_to_heal``,
        ``degraded_reads``, ``repair_bytes``, ``healed`` etc. from
        ``chaos.ChaosRuntime.soak`` land under the snapshot's ``chaos``
        key (the ``{health}`` verb and ``lasp_tpu top`` read it)."""
        with self._lock:
            self._check_generation()
            self.chaos.update(report)
            self.chaos["round"] = self.round

    def observe_quorum(self, **report) -> None:
        """Fold a quorum coordination report into the health surface —
        latency percentiles, completion/failure counts, repair and
        replication traffic, hint-log state from
        ``quorum.QuorumRuntime.report`` land under the snapshot's
        ``quorum`` key (the ``{health}`` verb and ``lasp_tpu top``
        read it alongside ``chaos``)."""
        with self._lock:
            self._check_generation()
            self.quorum.update(report)
            self.quorum["round"] = self.round

    def observe_serve(self, **report) -> None:
        """Fold a serving front-end's accounting into the health
        surface — offered/completed/shed/expired counts, parked
        watches, and the degradation-ladder level from
        ``serve.ServeFrontend.report`` land under the snapshot's
        ``serve`` key (the ``{health}`` verb and ``lasp_tpu top`` read
        it alongside ``chaos`` and ``quorum``)."""
        with self._lock:
            self._check_generation()
            self.serve.update(report)
            self.serve["round"] = self.round

    def observe_aae(self, **report) -> None:
        """Fold an active-anti-entropy report into the health surface —
        scrub counts, corruption detections/incidents, pending and
        applied repairs, repair-vs-resync traffic, and hash work by
        mode from ``aae.AAEScrubber.report`` land under the snapshot's
        ``aae`` key (the ``{health}`` verb and ``lasp_tpu top`` read it
        alongside ``chaos``/``quorum``/``serve``)."""
        with self._lock:
            self._check_generation()
            self.aae.update(report)
            self.aae["round"] = self.round

    def observe_ingest(self, *, ops: int, dispatches: int,
                       grouped_vars: int, fallback_vars: int,
                       pad_slots: int = 0, table_slots: int = 0) -> None:
        """Fold one grouped-ingest cycle's accounting into the health
        surface (``ReplicatedRuntime.ingest_cycle``). CUMULATIVE on
        purpose — unlike the latest-report sections, ingest is a
        per-cycle hot path and operators want rates, so the snapshot
        carries running totals plus the derived occupancy/pad views
        under the ``ingest`` key (the ``{health}`` verb and ``lasp_tpu
        top`` read it alongside ``serve``)."""
        with self._lock:
            self._check_generation()
            ing = self.ingest
            ing["cycles"] = ing.get("cycles", 0) + 1
            ing["ops"] = ing.get("ops", 0) + int(ops)
            ing["dispatches"] = ing.get("dispatches", 0) + int(dispatches)
            ing["grouped_vars"] = (
                ing.get("grouped_vars", 0) + int(grouped_vars)
            )
            ing["fallback_vars"] = (
                ing.get("fallback_vars", 0) + int(fallback_vars)
            )
            ing["pad_slots"] = ing.get("pad_slots", 0) + int(pad_slots)
            ing["table_slots"] = (
                ing.get("table_slots", 0) + int(table_slots)
            )
            if ing["dispatches"]:
                ing["vars_per_dispatch"] = round(
                    ing["grouped_vars"] / ing["dispatches"], 3
                )
            if ing["table_slots"]:
                ing["pad_frac"] = round(
                    ing["pad_slots"] / ing["table_slots"], 4
                )
            ing["round"] = self.round

    def observe_membership(self, kind: str, old_n: int, new_n: int) -> None:
        with self._lock:
            self._check_generation()
            self.memberships.append((self.round, kind, int(old_n), int(new_n)))
            del self.memberships[: -self.history]
            self.n_replicas = int(new_n)
            # lag/staleness accumulated against the old population no
            # longer means anything row-wise; keep per-var stats (they
            # are population-sums) but drop the stale probe
            self.last_probe = None

    # -- cached gauges (generation-keyed, like the runtime's cache) ----------
    def _set_gauges(self) -> None:
        if not _registry.enabled():
            return
        tel = self._tel
        if tel is None or tel["vars"] != tuple(self.vars):
            reg = _registry.get_registry()
            tel = self._tel = {
                "vars": tuple(self.vars),
                "stale": {
                    v: reg.gauge(
                        "convergence_staleness",
                        help="rounds since the variable's state last "
                             "changed anywhere (rounds since inflation)",
                        var=v,
                    )
                    for v in self.vars
                },
                "eta": reg.gauge(
                    "convergence_quiescence_eta_rounds",
                    help="estimated rounds to quiescence from the "
                         "residual decay (-1: no converging trend)",
                ),
            }
        for v, ent in self.vars.items():
            tel["stale"][v].set(self.round - ent["last_change_round"])
        eta = self._eta_locked()
        tel["eta"].set(-1 if eta is None else eta)

    # -- derived views -------------------------------------------------------
    def staleness(self) -> dict:
        """``{var: rounds since its state last changed}``."""
        with self._lock:
            return {
                v: self.round - ent["last_change_round"]
                for v, ent in self.vars.items()
            }

    def top_divergent(self, k: "int | None" = None) -> list:
        """``[(var, residual), ...]`` — the variables the last observed
        round changed at the most replicas, descending."""
        with self._lock:
            out = sorted(
                ((v, ent["residual"]) for v, ent in self.vars.items()),
                key=lambda x: (-x[1], x[0]),
            )
        return out[: (k or self.top_k)]

    def quiescence_eta(self) -> "int | None":
        with self._lock:
            return self._eta_locked()

    def _eta_locked(self) -> "int | None":
        """Geometric extrapolation of the total-residual decay. None
        when there is no converging trend (too little history, residual
        growing, or opaque -1 markers at the tail)."""
        if self.residual_curve and self.residual_curve[-1][1] < 0:
            # the LAST observation is an opaque non-quiescent marker
            # (fused block ran out without reaching the fixed point):
            # the current residual is unknown, and an older zero point
            # must not read as "converged"
            return None
        pts = [(r, t) for r, t in self.residual_curve[-8:] if t >= 0]
        if not pts:
            return None
        if pts[-1][1] == 0:
            return 0
        if len(pts) < 2:
            return None
        (r0, t0), (r1, t1) = pts[-2], pts[-1]
        if t1 >= t0 or t0 <= 0 or r1 <= r0:
            return None
        decay = (t1 / t0) ** (1.0 / (r1 - r0))  # per-round contraction
        if decay >= 1.0:
            return None
        # rounds until the residual extrapolates below 1
        eta = math.ceil(math.log(1.0 / t1) / math.log(decay))
        return max(1, min(eta, 100_000))

    # -- on-demand population probe ------------------------------------------
    def probe(self, runtime, n_shards: "int | None" = None) -> dict:
        """Compare every replica row against the global join, per
        variable: ``lag[r]`` = number of variables replica ``r`` is
        behind on. Aggregates per shard (contiguous row blocks, the
        runtime's partition plan shard count by default). One device
        reduction per variable — an on-demand surface (the ``top`` CLI,
        the ``{health}`` verb), never the per-round hot path."""
        import numpy as np

        from ..mesh.gossip import diverged_rows

        if n_shards is None:
            part = getattr(runtime, "_partition", None)
            n_shards = part["plan"]["n_shards"] if part else 1
        n = runtime.n_replicas
        lag = np.zeros((n,), dtype=np.int64)
        per_var: dict = {}
        for v in runtime.var_ids:
            codec, spec = runtime._mesh_meta(v)
            behind = np.asarray(
                diverged_rows(codec, spec, runtime._population(v))
            ).astype(np.int64)
            lag += behind
            per_var[v] = int(behind.sum())
        shard_lag = []
        if n_shards and n_shards > 0 and n:
            # contiguous near-equal blocks; a non-dividing population
            # splits with remainder rows in the leading shards rather
            # than silently dropping the aggregation
            shard_lag = [
                int(chunk.max(initial=0))
                for chunk in np.array_split(lag, min(int(n_shards), n))
            ]
        worst = int(lag.max(initial=0))
        probe = {
            "round": self.round,
            "n_replicas": n,
            "n_shards": int(n_shards or 1),
            "lag_by_var": per_var,
            "worst_replica": int(lag.argmax()) if n else 0,
            "worst_replica_lag": worst,
            "mean_replica_lag": round(float(lag.mean()), 4) if n else 0.0,
            "shard_lag": shard_lag,
        }
        part = getattr(runtime, "_partition", None)
        masks = getattr(runtime, "_frontier", None)
        if part is not None and masks:
            # dirty ∩ cut: how many boundary-exchange rows actually carry
            # new state — a full cut with an empty intersection means the
            # exchange ships pure no-ops (the delta-gossip waste signal)
            from ..mesh.shard_gossip import frontier_cut_rows

            union = np.zeros((n,), dtype=bool)
            for m in masks.values():
                if m.shape[0] == n:
                    union |= m
            probe["frontier_cut_rows"] = frontier_cut_rows(
                union, part["plan"]
            )
            probe["cut_rows"] = int(part["plan"]["stats"]["send_rows"])
            # the sparse exchange's cumulative wire ledger: what the
            # sharded-frontier rounds actually moved vs what the dense
            # cut plane would have, plus the interior/boundary split of
            # the overlapped joins (exchange-vs-interior overlap headroom)
            moved = getattr(runtime, "part_exchange_bytes_total", 0)
            plane = getattr(runtime, "part_dense_plane_bytes_total", 0)
            ir = getattr(runtime, "part_interior_rows_total", 0)
            br = getattr(runtime, "part_boundary_rows_total", 0)
            probe["shard_exchange"] = {
                "payload_bytes_total": int(moved),
                "dense_plane_bytes_total": int(plane),
                "wire_cut": (
                    round(plane / moved, 2) if moved else None
                ),
                "interior_rows_total": int(ir),
                "boundary_rows_total": int(br),
                "interior_overlap_frac": (
                    round(ir / (ir + br), 4) if (ir + br) else None
                ),
            }
        if _registry.enabled():
            reg = _registry.get_registry()
            for v, behind in per_var.items():
                reg.gauge(
                    "convergence_lag_replicas",
                    help="replica rows behind the global join, per var "
                         "(on-demand probe)",
                    var=v,
                ).set(behind)
            for s, sl in enumerate(shard_lag):
                reg.gauge(
                    "convergence_shard_lag",
                    help="worst per-replica lag inside each contiguous "
                         "shard block (on-demand probe)",
                    shard=s,
                ).set(sl)
        with self._lock:
            self._check_generation()
            self.last_probe = probe
        return probe

    # -- alerts ---------------------------------------------------------------
    def add_alert(self, name: str, fn) -> None:
        """Register ``fn(snapshot) -> bool`` — True raises alert
        ``name`` in :meth:`alerts` output."""
        self._alert_fns.append((str(name), fn))

    def alerts(self, snap: "dict | None" = None) -> list:
        """Alert lines for ``snap`` (default: a fresh snapshot — pass
        one to evaluate alerts against exactly the state a caller is
        about to report, as :meth:`health` does)."""
        if snap is None:
            snap = self.snapshot()
        out = []
        thr = self.thresholds
        max_stale = thr["max_stale_rounds"]
        probe = snap.get("probe")
        lag_by_var = (probe or {}).get("lag_by_var", {})
        if max_stale is not None:
            for v, stale in snap["staleness"].items():
                if stale < max_stale:
                    continue
                # staleness only alarms while the variable is DIVERGED:
                # quiescent-and-stale is just "done". Without a probe,
                # a nonzero last residual is the divergence signal.
                diverged = (
                    lag_by_var.get(v, 0) > 0
                    if probe is not None
                    else snap["residual_by_var"].get(v, 0) > 0
                )
                if diverged:
                    out.append(
                        f"stuck: {v} diverged but unchanged for "
                        f"{stale} rounds"
                    )
        max_lag = thr["max_replica_lag"]
        if max_lag is not None and probe is not None:
            if probe["worst_replica_lag"] > max_lag:
                out.append(
                    f"lagging: replica {probe['worst_replica']} is "
                    f"{probe['worst_replica_lag']} variables behind "
                    f"(threshold {max_lag})"
                )
        max_frac = thr["max_residual_frac"]
        if (
            max_frac is not None
            and snap["n_replicas"]
            and snap["residual_total"] is not None
            and snap["residual_total"]
            >= max_frac * snap["n_replicas"]
            and min(snap["staleness"].values(), default=0) == 0
            and snap["round"] >= (max_stale or 0)
            and (snap["quiescence_eta"] is None)
        ):
            out.append(
                f"thrashing: residual {snap['residual_total']} is not "
                f"contracting at round {snap['round']}"
            )
        for name, fn in self._alert_fns:
            try:
                if fn(snap):
                    out.append(name)
            except Exception as exc:  # a broken alert must not kill health
                out.append(f"alert {name!r} raised {type(exc).__name__}")
        return out

    # -- the exported view ----------------------------------------------------
    def snapshot(self) -> dict:
        """The full monitor state as plain data — what ``{health}``,
        ``lasp_tpu top`` and the bench artifact embed."""
        with self._lock:
            self._check_generation()
            curve = list(self.residual_curve)
            total = curve[-1][1] if curve else None
            if total is not None and total < 0:
                total = None  # opaque tail: unknown residual
            return {
                "round": self.round,
                "n_replicas": self.n_replicas,
                "residual_total": total,
                "residual_by_var": {
                    v: ent["residual"] for v, ent in self.vars.items()
                },
                "staleness": {
                    v: self.round - ent["last_change_round"]
                    for v, ent in self.vars.items()
                },
                "total_changes_by_var": {
                    v: ent["total_changes"] for v, ent in self.vars.items()
                },
                "top_divergent": sorted(
                    ((v, ent["residual"]) for v, ent in self.vars.items()),
                    key=lambda x: (-x[1], x[0]),
                )[: self.top_k],
                "quiescence_eta": self._eta_locked(),
                "frontier_by_var": dict(self.frontier),
                "chaos": dict(self.chaos),
                "quorum": dict(self.quorum),
                "serve": dict(self.serve),
                "aae": dict(self.aae),
                "ingest": dict(self.ingest),
                "residual_curve": curve[-64:],
                "memberships": list(self.memberships),
                "probe": self.last_probe,
                "thresholds": dict(self.thresholds),
            }

    def health(self) -> dict:
        """Snapshot + alerts — the one-call surface of the bridge's
        ``{health}`` verb and ``Session.health()``."""
        snap = self.snapshot()
        # alerts judge the SAME snapshot the payload carries: a scrape
        # concurrent with stepping must never pair round-N fields with
        # round-N+1 alerts
        snap["alerts"] = self.alerts(snap)
        # the roofline view: the kernel cost ledger's condensation
        # (lazy import — the ledger must never be a reason this module
        # fails to load in a lightweight process)
        try:
            from .roofline import get_ledger

            snap["roofline"] = get_ledger().summary()
        except Exception:
            snap["roofline"] = None
        return snap


# ---------------------------------------------------------------------------
# process-global monitor (the registry pattern: one sink, many feeders)
# ---------------------------------------------------------------------------

_monitor = ConvergenceMonitor()


def get_monitor() -> ConvergenceMonitor:
    return _monitor


def record_membership(kind: str, old_n: int, new_n: int, **attrs) -> None:
    """The one emission point for population membership changes: feeds
    the global monitor AND the causal event log, so resize callers
    (``ReplicatedRuntime.resize``, elastic checkpoint restore) cannot
    drop or double one of the two."""
    _monitor.observe_membership(kind, old_n, new_n)
    _events.emit(
        "membership", kind=kind, old_n=int(old_n), new_n=int(new_n), **attrs
    )
