"""Typed, bounded causal event log — "what happened to this variable,
in what order, at which replica".

The metric registry answers *how much* and *how fast*; this log answers
*why*: which client op, merge, gossip delivery, threshold firing, or
membership change produced the state an operator is staring at. It is
the TPU rebuild of the introspection the reference scatters across
lager log lines, the update FSM's read-repair trace
(``src/lasp_update_fsm.erl:189-216``) and ``lasp_process``
notifications — as one ordered, bounded, exportable record stream.

Design rules (the PR-1 hot-path contract):

- **typed**: every record's ``etype`` must be one of :data:`EVENT_TYPES`
  — an unknown type is a loud ``ValueError`` at the emission site, and
  the type set is linted against docs/OBSERVABILITY.md by
  ``tools/check_metrics_catalog.py`` (Makefile ``verify``);
- **bounded**: records land in a ring (default 4096, oldest dropped,
  drops counted) — a long-lived process never grows without bound;
- **ordered**: a process-wide monotone ``seq`` stamps every record
  under the ring lock, so interleaved emitters (bridge connection
  threads, mesh batch dispatch) totally order;
- **cheap**: one lock + one dict append per event; per-op granularity
  (individual batch ops, per-edge recomputes, host merges) is the
  DEEP tier — :func:`emit_deep` no-ops unless :func:`set_deep` (or the
  ``LASP_EVENTS_DEEP`` env var) turned it on, so the hot paths pay one
  coarse event per dispatch, not one per op;
- **off-switch**: :func:`registry.set_enabled(False)` silences the log
  together with the instruments (the overhead guard's off arm).

Sinks: the ring (:func:`events`), an optional JSONL file
(``LASP_EVENTS_JSONL`` or :func:`configure`), and
:func:`export_chrome_trace` — Perfetto / ``chrome://tracing`` JSON of
events (instant markers) interleaved with the span ring (duration
slices), the offline surface behind ``lasp_tpu trace --var``.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from . import registry as _registry
from .sink import JsonlSink

DEFAULT_RING_SIZE = 4096

#: the event taxonomy — every name here must have a row in the Event
#: catalog table of docs/OBSERVABILITY.md (linted both ways)
EVENT_TYPES = frozenset({
    "bind",            # store bind verb resolved (inflated / ignored)
    "update",          # client op(s) applied (store or mesh row)
    "merge",           # DEEP: one host-path CRDT merge
    "delivery",        # one gossip step/block dispatch delivered states
    "threshold_fire",  # a watch / blocking read / trigger threshold met
    "membership",      # resize / partition plan / checkpoint restore
    "propagate",       # one dataflow propagate-to-fixpoint run
    "propagate_sweep", # one fused sweep's per-dst changed flags (flight drain)
    "edge_recompute",  # DEEP: one edge's recompute provenance
    "frontier_skip",   # dirty-set scheduling skipped vars/edges outright
    "chaos",           # fault injected/healed, crash/restore, degraded read
    "quorum",          # quorum FSM round summary / hinted handoff replay
    "serve",           # one serving-cycle summary (writes/reads/fires/shed)
    "aae",             # anti-entropy scrub/detect/incident lifecycle
})

_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=DEFAULT_RING_SIZE)
_seq = 0
_dropped = 0
_round = 0
_deep = os.environ.get("LASP_EVENTS_DEEP", "") not in ("", "0", "false")
_sink = JsonlSink("LASP_EVENTS_JSONL")
#: cached per-etype counters, keyed on the registry generation (the
#: same detach-on-reset discipline as the runtime's instrument cache)
_counters: "tuple | None" = None
_dropped_counters: "tuple | None" = None


def configure(jsonl_path: "str | None" = None,
              ring_size: "int | None" = None) -> None:
    """(Re)configure the sinks — same contract as ``spans.configure``:
    ``jsonl_path=None`` keeps the current file, ``""`` disables it."""
    global _ring
    _sink.configure(jsonl_path)
    if ring_size is not None:
        with _lock:
            _ring = collections.deque(_ring, maxlen=int(ring_size))


def set_deep(flag: bool) -> None:
    """Deep tracing switch: per-op / per-merge / per-edge events. Off by
    default — at population scale the deep tier emits per CLIENT OP and
    would dominate the hot path the overhead guard protects."""
    global _deep
    _deep = bool(flag)


def deep_enabled() -> bool:
    return _deep


def set_round(n: int) -> None:
    """Advance the process-level logical round clock (the gossip round
    counter events are stamped with). The mesh runtime advances it once
    per executed round; emitters may also pass an explicit ``round=``."""
    global _round
    with _lock:
        _round = int(n)


def current_round() -> int:
    return _round


def _counter_for(etype: str):
    global _counters
    gen = _registry.generation()
    if _counters is None or _counters[0] != gen:
        _counters = (gen, {})
    cache = _counters[1]
    c = cache.get(etype)
    if c is None:
        c = cache[etype] = _registry.get_registry().counter(
            "events_emitted_total",
            help="causal event-log records emitted, by event type",
            etype=etype,
        )
    return c


def _dropped_counter():
    global _dropped_counters
    gen = _registry.generation()
    if _dropped_counters is None or _dropped_counters[0] != gen:
        _dropped_counters = (gen, _registry.get_registry().counter(
            "events_dropped_total",
            help="causal event-log records evicted from the bounded "
                 "ring (oldest-first) — a nonzero rate means forensics "
                 "are incomplete; raise the ring size or drain sooner",
        ))
    return _dropped_counters[1]


def emit(etype: str, *, var=None, replica=None, shard=None,
         round: "int | None" = None, **attrs) -> None:
    """Append one event record. ``var``/``replica``/``shard`` are the
    provenance columns every consumer filters on; anything else rides in
    ``attrs``. No-ops when telemetry is disabled."""
    if etype not in EVENT_TYPES:
        raise ValueError(
            f"unknown event type {etype!r} (known: {sorted(EVENT_TYPES)}) "
            "— add it to EVENT_TYPES and the docs/OBSERVABILITY.md catalog"
        )
    if not _registry.enabled():
        return
    global _seq, _dropped
    rec: dict = {"kind": "event", "etype": etype, "ts": round_ts()}
    if var is not None:
        rec["var"] = var
    if replica is not None:
        rec["replica"] = int(replica)
    if shard is not None:
        rec["shard"] = int(shard)
    if attrs:
        rec["attrs"] = attrs
    with _lock:
        rec["round"] = _round if round is None else int(round)
        rec["seq"] = _seq
        _seq += 1
        dropped = len(_ring) == _ring.maxlen
        if dropped:
            _dropped += 1
        _ring.append(rec)
    _counter_for(etype).inc()
    if dropped:
        _dropped_counter().inc()
    _sink.append(rec)


def emit_deep(etype: str, **kw) -> None:
    """The deep tier: per-op granularity, off unless :func:`set_deep`."""
    if _deep:
        emit(etype, **kw)


def round_ts() -> float:
    return round(time.time(), 6)


def events(etype: "str | None" = None, var=None) -> list:
    """Snapshot of the ring (oldest first), optionally filtered by event
    type and/or provenance variable."""
    with _lock:
        out = list(_ring)
    if etype is not None:
        out = [r for r in out if r["etype"] == etype]
    if var is not None:
        out = [r for r in out if r.get("var") == var]
    return out


def stats() -> dict:
    with _lock:
        return {
            "ring": len(_ring),
            "ring_size": _ring.maxlen,
            "seq": _seq,
            "dropped": _dropped,
            "deep": _deep,
            "jsonl_path": _sink.path,
        }


def clear() -> None:
    """Drop the ring and reset the clocks (tests)."""
    global _seq, _dropped, _round
    with _lock:
        _ring.clear()
        _seq = 0
        _dropped = 0
        _round = 0


# ---------------------------------------------------------------------------
# causal history + Perfetto / Chrome-trace export
# ---------------------------------------------------------------------------

def causal_history(var, lineage: "dict | None" = None) -> list:
    """Every ringed event relevant to ``var``'s value: the variable's own
    records, records of every UPSTREAM variable per ``lineage`` (the
    ``Graph.lineage`` map ``{var: {"srcs": [...], ...}}`` — so a derived
    output's history reaches back through its combinator edges to the
    source updates), and population-level context (membership changes,
    deliveries, ``propagate`` summaries, and ``propagate_sweep``
    records — a FUSED propagate's per-round work is carried off-device
    by the flight-recorder ring (``telemetry.device``), so fused
    windows contribute REAL per-round/per-sweep records here, not just
    the collapsed summary), ordered by ``seq``."""
    wanted = {var}
    if lineage:
        wanted |= set(lineage)
        for entry in lineage.values():
            wanted.update(entry.get("srcs", ()))
    out = [
        r
        for r in events()
        if r.get("var") in wanted
        or (
            r.get("var") is None
            and r["etype"] in (
                "membership", "delivery", "propagate", "propagate_sweep",
            )
        )
    ]
    out.sort(key=lambda r: r["seq"])
    return out


def export_chrome_trace(fp, event_records: "list | None" = None,
                        span_records: "list | None" = None) -> int:
    """Write a Chrome-trace/Perfetto JSON object to ``fp``: span records
    become duration slices (``ph: "X"``), event records become instant
    markers (``ph: "i"``) carrying their provenance columns in ``args``.
    Defaults to the full rings. Returns the number of traceEvents."""
    import json

    from . import spans as _spans

    if event_records is None:
        event_records = events()
    if span_records is None:
        span_records = _spans.events()
    trace = []
    for rec in span_records:
        if rec.get("kind") != "span":
            continue
        trace.append({
            "name": rec["name"],
            "cat": "span",
            "ph": "X",
            "ts": rec["ts"] * 1e6,
            "dur": max(rec.get("seconds", 0.0), 0.0) * 1e6,
            "pid": 0,
            "tid": 0,
            "args": {
                "path": rec.get("path", rec["name"]),
                **rec.get("attrs", {}),
            },
        })
    for rec in event_records:
        args = {
            k: rec[k]
            for k in ("var", "replica", "shard", "round", "seq")
            if k in rec
        }
        args.update(rec.get("attrs", {}))
        trace.append({
            "name": rec["etype"],
            "cat": "event",
            "ph": "i",
            "s": "g",  # global-scope instant: visible at any zoom
            "ts": rec["ts"] * 1e6,
            "pid": 0,
            "tid": 0,
            "args": args,
        })
    trace.sort(key=lambda t: t["ts"])
    json.dump(
        {"traceEvents": trace, "displayTimeUnit": "ms"},
        fp,
        default=repr,
    )
    fp.write("\n")
    return len(trace)
