"""Hierarchical span tracing: bounded ring + append-only JSONL event log.

A span is a named, nestable timing region (``span("gossip.round")`` >
``span("merge.orswot")``); the nesting path is tracked per thread, so
the bridge's per-connection threads interleave without corrupting each
other's lineage. Finished spans land in

- a **bounded in-memory ring** (default 2048 events, oldest dropped) —
  the flight recorder the CLI dumps with ``lasp_tpu metrics --jsonl``;
- an optional **append-only JSONL file** (one JSON object per line) —
  configure with :func:`configure` or the ``LASP_TELEMETRY_JSONL`` env
  var; write failures disable the sink loudly once rather than breaking
  the traced operation.

``annotate=True`` additionally wraps the region in a
``jax.profiler.TraceAnnotation`` so spans show up inside XLA profiles —
only when jax is ALREADY imported (telemetry must never be the thing
that pulls jax into a lightweight process; see lasp_tpu/__init__.py's
lazy-import contract).

Span taxonomy (documented in docs/OBSERVABILITY.md): ``gossip.round``,
``gossip.converge``, ``merge.<crdt_type>``, ``mesh.update_batch``,
``dataflow.propagate``, ``bridge.<verb>``.
"""

from __future__ import annotations

import collections
import contextlib
import sys
import threading
import time

from . import registry as _registry
from .sink import JsonlSink

DEFAULT_RING_SIZE = 2048

_local = threading.local()
_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=DEFAULT_RING_SIZE)
#: the shared locked writer (telemetry/sink.py): serialize-and-write is
#: ONE critical section per record, so concurrent emitters (bridge
#: connection threads, mesh batch dispatch) can never interleave
#: partial lines — the same discipline the causal event log uses
_sink = JsonlSink("LASP_TELEMETRY_JSONL")


def configure(jsonl_path: "str | None" = None,
              ring_size: "int | None" = None) -> None:
    """(Re)configure the sinks. ``jsonl_path=None`` keeps any current
    file; pass ``""`` to close and disable the JSONL sink."""
    global _ring
    _sink.configure(jsonl_path)
    if ring_size is not None:
        with _lock:
            _ring = collections.deque(_ring, maxlen=int(ring_size))


def events() -> list:
    """Snapshot of the ring (oldest first)."""
    with _lock:
        return list(_ring)


def clear() -> None:
    with _lock:
        _ring.clear()


def _emit(rec: dict) -> None:
    with _lock:
        _ring.append(rec)
    _sink.append(rec)


@contextlib.contextmanager
def span(name: str, annotate: bool = False, **attrs):
    """Time a region as one span event. Nesting is tracked per thread
    (``path`` joins enclosing span names with ``>``); duration is
    recorded whether or not the body raises (a failed round's timing is
    exactly the one you want on a dashboard), with ``error`` set to the
    exception type when it does."""
    if not _registry.enabled():
        yield
        return
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    path = ">".join(stack + [name])
    stack.append(name)
    ann = None
    if annotate and "jax" in sys.modules:
        import jax

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    ts = time.time()
    t0 = time.perf_counter()
    err: "str | None" = None
    try:
        yield
    except BaseException as exc:
        err = type(exc).__name__
        raise
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        stack.pop()
        rec = {
            "kind": "span",
            "name": name,
            "path": path,
            "ts": round(ts, 6),
            "seconds": dt,
        }
        if err is not None:
            rec["error"] = err
        if attrs:
            rec["attrs"] = attrs
        _emit(rec)


def current_path() -> str:
    """``>``-joined names of the spans currently open on this thread."""
    return ">".join(getattr(_local, "stack", []))
