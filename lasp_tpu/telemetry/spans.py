"""Hierarchical span tracing: bounded ring + append-only JSONL event log.

A span is a named, nestable timing region (``span("gossip.round")`` >
``span("merge.orswot")``); the nesting path is tracked per thread, so
the bridge's per-connection threads interleave without corrupting each
other's lineage. Finished spans land in

- a **bounded in-memory ring** (default 2048 events, oldest dropped) —
  the flight recorder the CLI dumps with ``lasp_tpu metrics --jsonl``;
- an optional **append-only JSONL file** (one JSON object per line) —
  configure with :func:`configure` or the ``LASP_TELEMETRY_JSONL`` env
  var; write failures disable the sink loudly once rather than breaking
  the traced operation.

``annotate=True`` additionally wraps the region in a
``jax.profiler.TraceAnnotation`` so spans show up inside XLA profiles —
only when jax is ALREADY imported (telemetry must never be the thing
that pulls jax into a lightweight process; see lasp_tpu/__init__.py's
lazy-import contract).

Span taxonomy (documented in docs/OBSERVABILITY.md): ``gossip.round``,
``gossip.converge``, ``merge.<crdt_type>``, ``mesh.update_batch``,
``dataflow.propagate``, ``bridge.<verb>``.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import sys
import threading
import time

from . import registry as _registry

DEFAULT_RING_SIZE = 2048

_local = threading.local()
_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=DEFAULT_RING_SIZE)
_jsonl_path: "str | None" = None
_jsonl_file = None
_jsonl_checked = False


def configure(jsonl_path: "str | None" = None,
              ring_size: "int | None" = None) -> None:
    """(Re)configure the sinks. ``jsonl_path=None`` keeps any current
    file; pass ``""`` to close and disable the JSONL sink."""
    global _ring, _jsonl_path, _jsonl_file, _jsonl_checked
    with _lock:
        if ring_size is not None:
            _ring = collections.deque(_ring, maxlen=int(ring_size))
        if jsonl_path is not None:
            if _jsonl_file is not None:
                try:
                    _jsonl_file.close()
                except OSError:
                    pass
            _jsonl_file = None
            _jsonl_path = jsonl_path or None
            _jsonl_checked = True  # explicit configure beats the env var


def events() -> list:
    """Snapshot of the ring (oldest first)."""
    with _lock:
        return list(_ring)


def clear() -> None:
    with _lock:
        _ring.clear()


def _emit(rec: dict) -> None:
    global _jsonl_file, _jsonl_path, _jsonl_checked
    with _lock:
        _ring.append(rec)
        if not _jsonl_checked:
            # first event decides the env-var default exactly once
            _jsonl_path = os.environ.get("LASP_TELEMETRY_JSONL") or None
            _jsonl_checked = True
        if _jsonl_path is None:
            return
        try:
            if _jsonl_file is None:
                _jsonl_file = open(_jsonl_path, "a", buffering=1)
            _jsonl_file.write(json.dumps(rec) + "\n")
        except OSError as exc:
            # a broken sink must not break the traced operation — disable
            # it loudly ONCE instead of failing every span from now on
            print(
                f"lasp_tpu.telemetry: JSONL sink {_jsonl_path!r} failed "
                f"({exc}); span logging to file disabled",
                file=sys.stderr,
            )
            _jsonl_path = None
            _jsonl_file = None


@contextlib.contextmanager
def span(name: str, annotate: bool = False, **attrs):
    """Time a region as one span event. Nesting is tracked per thread
    (``path`` joins enclosing span names with ``>``); duration is
    recorded whether or not the body raises (a failed round's timing is
    exactly the one you want on a dashboard), with ``error`` set to the
    exception type when it does."""
    if not _registry.enabled():
        yield
        return
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    path = ">".join(stack + [name])
    stack.append(name)
    ann = None
    if annotate and "jax" in sys.modules:
        import jax

        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    ts = time.time()
    t0 = time.perf_counter()
    err: "str | None" = None
    try:
        yield
    except BaseException as exc:
        err = type(exc).__name__
        raise
    finally:
        dt = time.perf_counter() - t0
        if ann is not None:
            ann.__exit__(None, None, None)
        stack.pop()
        rec = {
            "kind": "span",
            "name": name,
            "path": path,
            "ts": round(ts, 6),
            "seconds": dt,
        }
        if err is not None:
            rec["error"] = err
        if attrs:
            rec["attrs"] = attrs
        _emit(rec)


def current_path() -> str:
    """``>``-joined names of the spans currently open on this thread."""
    return ">".join(getattr(_local, "stack", []))
