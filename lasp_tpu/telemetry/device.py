"""Device-resident telemetry plane: stats-carry rings + the flight
recorder (the in-graph counters PR).

Every fused execution path — ``fused_steps`` blocks, the
``converge_on_device`` while loops (global and sharded), the dataflow
propagate megakernel, chaos windows — used to report OPAQUE rounds:
per-round residuals never reached the host, so the ConvergenceMonitor
recorded only a terminal quiescent/unconverged marker and the causal
log one coarse delivery record per dispatch. This module closes that
blind spot without adding a single host sync:

- **stats carry** — :func:`ring_init` / :func:`ring_write` build a
  small fixed-layout ``int32[K, W]`` buffer INSIDE the traced loop
  body and thread it as extra carry state (DrJAX's move — PAPERS.md —
  keep the accumulators traceable primitives inside the compiled
  graph). One dynamic row update per round; the buffer is created in
  the jit, so donation layouts are untouched.
- **flight recorder** — the buffer is a modulo-``K`` ring over rounds:
  the LAST ``K`` per-round records survive any window length, and the
  sharded converge path folds them through the same log-depth ``psum``
  tree the quiescence reduction already pays for (the Tascade move —
  no extra barrier).
- **host drain** — :func:`decode_ring` unwraps the ring on the device
  sync each dispatch already performs;
  ``ReplicatedRuntime._drain_flight`` feeds the decoded rounds into
  the metric registry, ``ConvergenceMonitor.observe_round`` (real
  residual-curve points, bit-for-bit identical to unfused stepping),
  per-round ``delivery`` events, the kernel ledger's exact join
  tallies, and this module's bounded window log — the post-incident
  forensics surface behind ``lasp_tpu flight``.

Hot-path note: the per-DISPATCH host cost is bounded by ``K`` (config
knob ``flight_rounds``), amortized over the window's rounds; the
``flight`` arm of ``telemetry.overhead.measure_overhead`` prices
exactly this drain against the 5% always-on budget.

The module never imports jax at module scope (the CLI --help /
lightweight-process rule); the traced helpers import it lazily at
trace time.
"""

from __future__ import annotations

import collections
import dataclasses
import threading

from . import registry as _registry

#: fallback flight-ring depth when no config is resolvable (the config
#: knob ``flight_rounds`` / env ``LASP_FLIGHT_ROUNDS`` is the real one)
DEFAULT_FLIGHT_ROUNDS = 64

#: host-side window log bound (windows, not rounds — one entry per
#: drained fused dispatch)
DEFAULT_LOG_WINDOWS = 256


def flight_rounds() -> int:
    """The configured ring depth ``K`` — last K rounds of per-round
    records survive each fused window."""
    from ..config import get_config

    return int(get_config().flight_rounds)


# ---------------------------------------------------------------------------
# traced helpers (called INSIDE jitted loop bodies; lazy jax imports)
# ---------------------------------------------------------------------------

def ring_init(n_rounds: int, width: int):
    """A fresh ``int32[K, W]`` flight ring. Call inside the traced
    function — the buffer is then a jit-internal value and never shows
    up in the donation signature."""
    import jax.numpy as jnp

    return jnp.zeros((int(n_rounds), int(width)), jnp.int32)


def ring_write(ring, round_index, record):
    """Write one round's record at ``round_index % K`` (the modulo ring:
    the last K rounds survive any window length). ``record`` is any
    integer vector of width W — the per-var residual vector, per-dst
    changed flags, etc."""
    import jax
    import jax.numpy as jnp

    rec = jnp.asarray(record).astype(jnp.int32)
    k = ring.shape[0]
    return jax.lax.dynamic_update_index_in_dim(
        ring, rec, jnp.mod(round_index, k), 0
    )


def decode_ring(ring, rounds: int):
    """Host-side unwrap of a drained ring: ``(records, overwritten)``
    where ``records`` is the retained per-round rows in ROUND ORDER
    (oldest first — the last ``min(rounds, K)`` rounds) and
    ``overwritten`` counts the prefix rounds the modulo ring lost.
    Round ``j`` lives at slot ``j % K``, so the retained suffix starts
    at slot ``(rounds - n) % K``."""
    import numpy as np

    arr = np.asarray(ring)
    k = int(arr.shape[0])
    rounds = int(rounds)
    n = max(min(rounds, k), 0)
    overwritten = max(rounds - k, 0)
    start = (rounds - n) % k if k else 0
    records = [
        [int(x) for x in arr[(start + i) % k]] for i in range(n)
    ]
    return records, overwritten


# ---------------------------------------------------------------------------
# the host-side window log (the forensics surface behind `lasp_tpu flight`)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FlightWindow:
    """One drained fused window: the per-round records that survived
    the ring plus the window's provenance."""

    family: str               # fused_block / converge / chaos_window / ...
    columns: tuple            # per-record column ids (var ids, dst names)
    rounds: int               # rounds the window executed
    overwritten: int          # prefix rounds the modulo ring lost
    records: list             # [retained][len(columns)] ints, round order
    seconds: float            # window wall time
    quiescent: "bool | None"  # reached the fixed point? None = n/a
    first_round: int = 0      # monitor round of records[0] (0 = unclocked)
    meta: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "family": self.family,
            "columns": list(self.columns),
            "rounds": int(self.rounds),
            "overwritten": int(self.overwritten),
            "records": [list(r) for r in self.records],
            "seconds": round(float(self.seconds), 6),
            "quiescent": self.quiescent,
            "first_round": int(self.first_round),
            "meta": dict(self.meta),
        }

    def residual_curve(self) -> list:
        """``[(round, total), ...]`` over the retained records — the
        same shape as ``ConvergenceMonitor.residual_curve``."""
        base = int(self.first_round)
        return [
            (base + i, int(sum(rec))) for i, rec in enumerate(self.records)
        ]


_lock = threading.Lock()
#: (registry generation, deque-of-FlightWindow) — generation-keyed like
#: every other telemetry cache, so a test-time ``telemetry.reset()`` (or
#: the overhead guard's scratch registry) detaches accumulated windows
_log: "tuple | None" = None


def _windows_locked() -> collections.deque:
    global _log
    gen = _registry.generation()
    if _log is None or _log[0] != gen:
        _log = (gen, collections.deque(maxlen=DEFAULT_LOG_WINDOWS))
    return _log[1]


def record_window(window: FlightWindow) -> None:
    """Append one drained window and bump the flight counters. No-ops
    when telemetry is disabled (the off-switch contract)."""
    if not _registry.enabled():
        return
    with _lock:
        _windows_locked().append(window)
    reg = _registry.get_registry()
    reg.counter(
        "flight_windows_total",
        help="fused windows drained through the flight recorder, by "
             "kernel family",
        family=window.family,
    ).inc()
    reg.counter(
        "flight_rounds_recorded_total",
        help="per-round flight records decoded host-side (retained "
             "ring rows across all drained windows)",
    ).inc(len(window.records))
    if window.overwritten:
        reg.counter(
            "flight_rounds_overwritten_total",
            help="rounds whose flight records the modulo-K ring "
                 "overwrote before the drain (window longer than "
                 "flight_rounds)",
        ).inc(window.overwritten)


def windows(family: "str | None" = None) -> list:
    """Snapshot of the window log (oldest first), optionally filtered
    by kernel family."""
    with _lock:
        out = list(_windows_locked())
    if family is not None:
        out = [w for w in out if w.family == family]
    return out


def last_window(family: "str | None" = None) -> "FlightWindow | None":
    ws = windows(family)
    return ws[-1] if ws else None


def clear() -> None:
    """Drop the window log (tests / fresh forensics baseline)."""
    with _lock:
        _windows_locked().clear()


def stats() -> dict:
    with _lock:
        ws = list(_windows_locked())
    return {
        "windows": len(ws),
        "log_size": DEFAULT_LOG_WINDOWS,
        "rounds_recorded": sum(len(w.records) for w in ws),
        "rounds_overwritten": sum(w.overwritten for w in ws),
        "families": sorted({w.family for w in ws}),
    }


def snapshot() -> dict:
    """The full recorder as plain data — the ``lasp_tpu flight
    --export`` artifact."""
    return {
        "flight_rounds": flight_rounds(),
        "stats": stats(),
        "windows": [w.to_dict() for w in windows()],
    }


def render(ws: "list | None" = None, max_columns: int = 8) -> str:
    """Human dump of the recorder: one block per window, one line per
    retained round (round clock, total residual, leading per-column
    counts) — the `lasp_tpu flight` output."""
    if ws is None:
        ws = windows()
    if not ws:
        return "flight recorder: no fused windows drained yet"
    lines: list = []
    for i, w in enumerate(ws):
        q = {True: "quiescent", False: "unconverged", None: "-"}[w.quiescent]
        lines.append(
            f"window {i}: family={w.family} rounds={w.rounds} "
            f"retained={len(w.records)} overwritten={w.overwritten} "
            f"{q} {w.seconds * 1e3:.2f}ms"
        )
        cols = list(w.columns[:max_columns])
        if cols:
            more = len(w.columns) - len(cols)
            suffix = f" (+{more} more)" if more > 0 else ""
            lines.append("  round  total  " + "  ".join(cols) + suffix)
        for j, rec in enumerate(w.records):
            rnd = w.first_round + j if w.first_round else j
            head = "  ".join(str(x) for x in rec[:max_columns])
            lines.append(f"  {rnd:>5}  {sum(rec):>5}  {head}")
        if w.meta:
            meta = " ".join(f"{k}={v}" for k, v in sorted(w.meta.items()))
            lines.append(f"  meta: {meta}")
    return "\n".join(lines)
