"""Unified telemetry: metric registry, span tracing, Prometheus/JSONL
export (SURVEY.md §5 auxiliary subsystems, rebuilt as a first-class
layer).

One import surface for every emitter and consumer:

- ``counter/gauge/histogram`` — typed instruments on the process-global
  registry (see :mod:`.registry`); ``set_enabled(False)`` flips them to
  no-ops (the bench overhead guard's off arm).
- ``span`` — nested timing regions into a bounded ring + optional JSONL
  log (see :mod:`.spans`); ``annotate=True`` adds a ``jax.profiler``
  annotation when jax is already imported.
- ``events`` — the typed, bounded CAUSAL EVENT LOG (see :mod:`.events`):
  bind / update / delivery / threshold-fire / membership records with
  logical-round + replica/shard provenance, JSONL sink and
  Perfetto/Chrome-trace export (``lasp_tpu trace``).
- ``get_monitor()`` — the process-global :class:`ConvergenceMonitor`
  (see :mod:`.convergence`): per-variable residual/staleness, divergence
  top-K, quiescence ETA, per-replica/per-shard lag probes, pluggable
  alerts — the state behind the bridge's ``{health}`` verb and
  ``lasp_tpu top``.
- ``render_prometheus`` / ``dump_jsonl`` — the scrape/offline surfaces
  (see :mod:`.export`); served by the bridge's ``metrics`` verb and the
  ``lasp_tpu metrics`` CLI.
- ``profile`` — the ``jax.profiler`` block tracer (re-exported from
  ``utils.metrics``, where the legacy import path keeps working).

This package never imports jax at module scope: telemetry must be
importable by the lightweight processes (CLI --help, the bench parent)
that the lazy package __init__ protects.

The metric catalog and span taxonomy live in docs/OBSERVABILITY.md;
``tools/check_metrics_catalog.py`` keeps code and catalog in lock-step.
"""

from __future__ import annotations

from . import convergence, device, events
from .capability import device_capability, peak_gbps_for_kind
from .convergence import ConvergenceMonitor, get_monitor
from .export import dump_jsonl, metric_events, render_prometheus
from .roofline import (
    KernelLedger,
    capture_scenario,
    get_ledger,
    kernel_traffic,
    profile_capture,
)
from .registry import (
    DEFAULT_BUCKETS,
    Counter,
    CounterGroup,
    Gauge,
    Histogram,
    MetricRegistry,
    counter,
    enabled,
    gauge,
    get_registry,
    histogram,
    reset,
    set_enabled,
)
from .spans import clear as clear_spans
from .spans import configure, current_path, span
from .spans import events as span_events
from ..utils.metrics import profile

__all__ = [
    "ConvergenceMonitor",
    "DEFAULT_BUCKETS",
    "Counter",
    "CounterGroup",
    "KernelLedger",
    "capture_scenario",
    "convergence",
    "device",
    "device_capability",
    "events",
    "get_ledger",
    "get_monitor",
    "kernel_traffic",
    "peak_gbps_for_kind",
    "profile_capture",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "clear_spans",
    "configure",
    "counter",
    "current_path",
    "dump_jsonl",
    "enabled",
    "events",
    "gauge",
    "get_registry",
    "histogram",
    "metric_events",
    "profile",
    "render_prometheus",
    "reset",
    "set_enabled",
    "span",
    "span_events",
]
