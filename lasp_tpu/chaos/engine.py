"""ChaosRuntime: drive a replicated population through a fault timeline.

Wraps a :class:`~lasp_tpu.mesh.runtime.ReplicatedRuntime` with a
:class:`~lasp_tpu.chaos.schedule.ChaosSchedule`: each chaos round
processes the round's crash/restore actions, compiles the round's fault
state into the edge mask the existing gossip kernels accept, and
dispatches the runtime's OWN step (dense or frontier — no chaos-specific
collective path). On top ride the replication-facing verbs the reference
gets from its quorum FSMs:

- **crash** (fail-stop): every link touching the replica dies, its row
  freezes (a crashed row with dataflow edges/triggers is snapshotted
  around the step so local sweeps cannot move it), client writes to it
  are refused, and its actor lanes are retired (the riak_dt
  never-reuse-an-actor incarnation rule, as in ``resize`` crash);
- **restore**: the row re-seeds from the lattice bottom or an attached
  runtime checkpoint's saved row (``store.checkpoint.load_runtime_rows``)
  and every frontier degrades to all-dirty — gossip then performs the
  hinted-handoff-style catch-up;
- **degraded reads**: :meth:`degraded_read` answers from K live
  replicas of a variable (Lasp's R=2 first-replies quorum,
  ``src/lasp_read_fsm.erl:125-146``) and triggers READ-REPAIR as a
  masked partial join — the quorum's join is merged back into exactly
  the rows read (``src/lasp_update_fsm.erl:189-216``), with the wire
  cost accounted per repaired row (``chaos_repair_bytes_total``).

Healing is measured, not assumed: :meth:`soak` runs the timeline to its
horizon and then to quiescence, reporting rounds-to-heal — and the
invariant harness (``chaos.invariants``) asserts the healed fixed point
is bit-identical to a fault-free run's.
"""

from __future__ import annotations

import numpy as np

from ..mesh.gossip import quorum_read, rows_traffic_bytes
from ..telemetry import counter, events as tel_events, gauge, span
from ..telemetry.convergence import get_monitor


class ReplicaDownError(RuntimeError):
    """A client verb targeted a crashed replica. The reference's FSMs
    route around a down vnode via the preflist; the simulation surfaces
    the routing decision to the caller instead (use
    :meth:`ChaosRuntime.degraded_read` / a live replica row)."""


class ChaosRuntime:
    """One population + one fault timeline; see the module doc.

    Donation is turned OFF on the wrapped runtime: chaos soaks are
    exactly the checkpoint-then-retry shape the donation trade-off note
    on ``ReplicatedRuntime.donate_steps`` warns about (crash freezing
    snapshots rows across a dispatch, and a failed dispatch mid-soak
    must not poison the run)."""

    def __init__(self, runtime, schedule, checkpoint: "str | None" = None):
        if runtime.n_replicas != schedule.n_replicas:
            raise ValueError(
                f"schedule is for {schedule.n_replicas} replicas, runtime "
                f"has {runtime.n_replicas}"
            )
        if not np.array_equal(
            np.asarray(schedule.neighbors), runtime._host_neighbors
        ):
            raise ValueError(
                "schedule was compiled for a different neighbor table — "
                "build it from this runtime's topology"
            )
        if runtime._partition is not None:
            raise ValueError(
                "partitioned boundary-exchange gossip bakes a dense row "
                "plan and cannot take per-round edge masks — shard with "
                "partition=False for chaos runs"
            )
        self.rt = runtime
        self.schedule = schedule
        #: runtime checkpoint path backing Restore(source="checkpoint")
        self.checkpoint = checkpoint
        # graceful-leave handoff guard (the PR4 degraded-read confinement
        # rule applied to membership): a resize merge under this wrapper
        # must not move departing state across an active partition or
        # out of a crashed row — a host-side tree_map bypassing the very
        # edge mask the nemesis installed. The staged membership path
        # (lasp_tpu.membership) parks such transfers instead. A
        # FAULT-FREE wrapper (the QuorumRuntime/MembershipCoordinator
        # convenience wrap: no events, so no masks and no crashes ever)
        # installs nothing — its guard would be vacuous, and overwriting
        # here would silently neuter the guard of a real nemesis wrapper
        # sharing the runtime.
        if schedule.events:
            runtime._handoff_guard = self._check_handoff
        #: the membership epoch this wrapper's bookkeeping is based on —
        #: the O(1) staleness guard of :meth:`sync_membership` (every
        #: membership commit, topology swaps included, advances the
        #: runtime's epoch; a full neighbor-table compare per round
        #: would tax every large chaos run for nothing)
        self._synced_epoch = runtime.membership_epoch
        if runtime.donate_steps:
            runtime.donate_steps = False
            runtime._step = None
            runtime._fused_steps_cache.clear()
        self.round = 0
        self.crashed = np.zeros(runtime.n_replicas, dtype=bool)
        #: rows restored at the LAST step — the invariant harness's
        #: monotonicity exemption (a reseed is deliberately non-monotone)
        self.last_restored: list = []
        #: (var, row) pairs corrupted at the LAST step (silent state
        #: mutation — CorruptRows/BitRot events); the AAE harness's
        #: ground-truth for detection/localization latency
        self.last_corrupted: list = []
        #: full injection ledger: {"round", "var", "row", "kind"}
        self.injected_corruptions: list = []
        #: attached AAE scrubber (``lasp_tpu.aae.AAEScrubber`` sets
        #: itself here): ``on_round_start`` runs after the round's
        #: actions/injections and BEFORE the gossip dispatch (a corrupt
        #: row detected there never gossips outward),
        #: ``on_round_end`` commits the round's tracked changes into
        #: the hash forest
        self.aae = None
        self.degraded_reads = 0
        self.repair_bytes = 0
        self.repaired_rows = 0
        self.duplicates_suppressed = 0
        self.crashes = 0
        self.restores = 0
        self._fused_cache: dict = {}

    # -- membership -----------------------------------------------------------
    def _check_handoff(self, sources, targets) -> None:
        """Refuse a graceful-leave handoff that would bypass the active
        fault state (installed as ``rt._handoff_guard``): a crashed
        departer's frozen row cannot be read gracefully, and a
        source→target pair spanning a partition cut would tunnel state
        through the mask host-side. Raises the typed
        :class:`~lasp_tpu.membership.errors.HandoffPartitionError`;
        callers either wait for heal, crash-leave explicitly, or run
        the staged coordinator (whose transfers park instead)."""
        from ..membership.errors import HandoffPartitionError

        # the runtime may have resized since the last round (a grow
        # commits without consulting this guard): judge against
        # bookkeeping re-based onto the CURRENT extent, never a stale
        # crashed vector / schedule
        self.sync_membership()
        down = [int(s) for s in sources if self.crashed[int(s)]]
        if down:
            raise HandoffPartitionError(
                f"graceful leave refused: departing replica(s) "
                f"{down[:4]} are crashed — their frozen rows cannot be "
                "handed off; restore them first or take "
                "graceful=False (crash-leave) semantics"
            )
        down = [int(t) for t in targets if self.crashed[int(t)]]
        if down:
            raise HandoffPartitionError(
                f"graceful leave refused: claim target(s) {down[:4]} "
                "are crashed — a handoff cannot land on a down row"
            )
        mask = self.schedule.mask_at(self.round)
        if mask is None:
            return
        from ..quorum.fsm import components

        comp = components(
            self.rt._host_neighbors, mask, ~self.crashed
        )
        bad = [
            (int(s), int(t)) for s, t in zip(sources, targets)
            if comp[int(s)] != comp[int(t)]
        ]
        if bad:
            raise HandoffPartitionError(
                f"graceful leave refused: handoff pair(s) {bad[:4]} "
                "span a partition under the active chaos mask — the "
                "merge would be a host-side side channel through the "
                "cut; wait for heal or run the staged "
                "MembershipCoordinator (transfers park until reachable)"
            )

    def sync_membership(self) -> bool:
        """Re-base this wrapper's fault bookkeeping onto the runtime's
        CURRENT membership (after a resize / staged commit): the
        crashed vector resizes (surviving rows keep their flags;
        dropped rows leave with theirs), and the schedule re-compiles
        against the new extent/topology (events naming departed
        replicas are dropped — their crash/restore can no longer
        apply). Returns True when anything changed. Called by the
        membership coordinator at commit and defensively by
        :meth:`step` — a stale [R, K] mask against a resized population
        would otherwise fail shapes rounds later."""
        if self.rt.membership_epoch == self._synced_epoch:
            return False
        self._synced_epoch = self.rt.membership_epoch
        R = self.rt.n_replicas
        nbrs = self.rt._host_neighbors
        if (
            self.crashed.shape[0] == R
            and self.schedule.n_replicas == R
            and np.array_equal(np.asarray(self.schedule.neighbors), nbrs)
        ):
            return False
        old = self.crashed
        keep = min(old.shape[0], R)
        crashed = np.zeros(R, dtype=bool)
        crashed[:keep] = old[:keep]
        self.crashed = crashed
        self.schedule = self.schedule.rebase(R, nbrs)
        # mask-identity entries and fused-window executables both bake
        # the old [R, K] shapes
        self._fused_cache.clear()
        return True

    # -- fault actions --------------------------------------------------------
    def _crash(self, replica: int) -> None:
        if self.crashed[replica]:
            raise RuntimeError(f"replica {replica} is already down")
        self.crashed[replica] = True
        self.crashes += 1
        # the riak_dt incarnation rule (the resize-crash discipline): the
        # dead row's minted tokens may still circulate via gossip, so its
        # actor lanes retire — a post-restore write under an old actor at
        # ANY row collides loudly instead of silently reusing slots
        for key, site in list(self.rt._actor_sites.items()):
            if site == int(replica):
                self.rt._actor_sites[key] = -1
        counter(
            "chaos_faults_injected_total",
            help="chaos fault events activated, by kind",
            kind="crash",
        ).inc()
        tel_events.emit(
            "chaos", replica=int(replica), action="crash",
            round=self.round,
        )

    def _restore(self, replica: int, source: str) -> None:
        if not self.crashed[replica]:
            raise RuntimeError(f"replica {replica} is not down")
        rows = None
        if source == "checkpoint":
            if self.checkpoint is None:
                raise RuntimeError(
                    "Restore(source='checkpoint') needs a checkpoint "
                    "path — pass ChaosRuntime(..., checkpoint=path)"
                )
            from ..store.checkpoint import load_runtime_rows

            rows = load_runtime_rows(self.checkpoint, replica)
        self.rt.reseed_row(replica, rows)
        self.crashed[replica] = False
        self.restores += 1
        self.last_restored.append(int(replica))
        counter(
            "chaos_faults_injected_total",
            help="chaos fault events activated, by kind",
            kind="restore",
        ).inc()
        tel_events.emit(
            "chaos", replica=int(replica), action="restore",
            round=self.round, source=source,
        )

    def _apply_actions(self, rnd: int) -> None:
        from .schedule import Crash

        self.last_restored = []
        self.last_corrupted = []
        for ev in self.schedule.actions_at(rnd):
            if isinstance(ev, Crash):
                self._crash(ev.replica)
            else:
                self._restore(ev.replica, ev.source)
        for idx, ev, shot in self.schedule.corruptions_at(rnd):
            self._inject_corruption(ev, idx, shot, rnd)

    # -- silent corruption (CorruptRows / BitRot) -----------------------------
    def _inject_corruption(self, ev, idx: int, shot: int,
                           rnd: int) -> None:
        """Apply one corruption event: mutate ``ev.n_rows`` seeded LIVE
        replica rows directly in device state, bypassing every
        dirty-tracking path (the point: nothing legitimate explains the
        change). Pure function of ``(seed, schedule, round, state)`` —
        replays bit-identically."""
        from .schedule import _mix

        live = np.flatnonzero(~self.crashed)
        if live.size == 0:
            return
        base = (
            (self.schedule.seed * 1_000_003 + idx * 7919)
            ^ ((rnd + 1) * 2_654_435)
        ) + shot * 65_537
        var_ids = (
            [ev.var] if ev.var is not None else list(self.rt.var_ids)
        )
        if not var_ids:
            return
        for j in range(int(ev.n_rows)):
            draw = _mix(
                np.asarray([j * 3 + 1, j * 3 + 2, j * 3 + 3],
                           dtype=np.uint64),
                base,
            )
            row = int(live[int(draw[0] * live.size) % live.size])
            var = var_ids[int(draw[1] * len(var_ids)) % len(var_ids)]
            salt = int(draw[2] * (1 << 31))
            if not self._mutate_row(var, row, ev.kind, salt):
                continue  # target held nothing to corrupt this way
            rec = {"round": int(rnd), "var": var, "row": row,
                   "kind": ev.kind}
            self.injected_corruptions.append(rec)
            self.last_corrupted.append((var, row))
            counter(
                "chaos_faults_injected_total",
                help="chaos fault events activated, by kind",
                kind="corrupt",
            ).inc()
            tel_events.emit(
                "chaos", var=var, replica=row, action="corrupt",
                kind=ev.kind, round=int(rnd),
            )

    def _mutate_row(self, var: str, row: int, kind: str,
                    salt: int) -> bool:
        """One row mutation by kind; returns False when the target row
        carried nothing this kind can corrupt (a rollback of an empty
        counter, a truncate of an empty plane — the injection is then
        skipped, never silently recorded as a no-op)."""
        import jax
        import jax.numpy as jnp

        rt = self.rt
        pop = rt._population(var)
        leaves = jax.tree_util.tree_leaves(pop)
        treedef = jax.tree_util.tree_structure(pop)
        host = [np.array(np.asarray(leaf[row])) for leaf in leaves]
        changed = False
        if kind == "bitflip":
            for off in range(len(host)):
                li = (salt + off) % len(host)
                flat = host[li].reshape(-1)
                if flat.size == 0:
                    continue
                pos = (salt // 7) % flat.size
                if flat.dtype == np.bool_:
                    flat[pos] = ~flat[pos]
                else:
                    # bits-1: np.int32(1 << 31) would overflow the
                    # scalar conversion for signed dtypes
                    bits = flat.dtype.itemsize * 8 - 1
                    flat[pos] = flat[pos] ^ flat.dtype.type(
                        1 << ((salt // 11) % bits)
                    )
                changed = True
                break
        elif kind == "rollback":
            # halve a positive integer lane (counter/clock rollback);
            # prefer the FIRST int leaf (gcounter counts, orswot clock)
            for off in range(len(host)):
                flat = host[off].reshape(-1)
                if flat.dtype == np.bool_ or flat.size == 0:
                    continue
                positive = np.flatnonzero(flat.astype(np.int64) > 0)
                if positive.size == 0:
                    continue
                pos = int(positive[(salt // 7) % positive.size])
                flat[pos] = flat[pos] // 2
                changed = True
                break
        elif kind == "truncate":
            # zero the tail half of the LAST wire plane (truncated dot
            # planes / token planes)
            flat = host[-1].reshape(-1)
            tail = flat[flat.size // 2:]
            if tail.size and np.any(tail != 0):
                tail[:] = 0
                changed = True
        else:
            raise ValueError(f"unknown corruption kind {kind!r}")
        if not changed:
            return False
        new_leaves = [
            leaf.at[row].set(jnp.asarray(h.reshape(leaf.shape[1:])))
            for leaf, h in zip(leaves, host)
        ]
        # direct state write ON PURPOSE: no mark_dirty, no _aae_mark —
        # the corruption is silent, which is exactly what the AAE
        # verify pass exists to catch
        rt.states[var] = jax.tree_util.tree_unflatten(
            treedef, new_leaves
        )
        return True

    def _needs_freeze(self) -> bool:
        """Gossip alone cannot move a crashed row (its every edge is
        masked); only local dataflow sweeps / triggers can — freeze is
        needed exactly then."""
        return bool(self.crashed.any()) and bool(
            self.rt.graph.edges or self.rt._triggers
        )

    def _account_duplicates(self, rnd: int, alive=None) -> None:
        """At-least-once accounting for one executed round: duplicated
        deliveries are no-ops under the idempotent join, so they only
        COUNT (the measured tolerance claim, docs/RESILIENCE.md)."""
        dup = self.schedule.duplicate_links_at(rnd, alive=alive)
        if dup:
            self.duplicates_suppressed += dup
            counter(
                "chaos_duplicate_deliveries_total",
                help="duplicated gossip deliveries absorbed by join "
                     "idempotence (DuplicateLinks accounting)",
            ).inc(dup)

    def _emit_round_gauges(self, mask) -> None:
        gauge(
            "chaos_replicas_crashed",
            help="replicas currently failed-stop under chaos",
        ).set(int(self.crashed.sum()))
        gauge(
            "chaos_links_dead",
            help="directed gossip edges dead under the current chaos "
                 "mask",
        ).set(0 if mask is None else int((~np.asarray(mask)).sum()))

    # -- stepping -------------------------------------------------------------
    def step(self, mode: str = "dense") -> int:
        """ONE chaos round: apply this round's crash/restore actions,
        compile the round's mask, dispatch the runtime's own step
        (``mode`` = ``"dense"`` | ``"frontier"``), and freeze crashed
        rows across it. Returns the step's residual (the engine
        contract). Deterministic in ``(seed, schedule, state)``."""
        rnd = self.round
        # a membership commit may have changed the extent since the last
        # round: re-base the fault bookkeeping before compiling masks
        self.sync_membership()
        self._apply_actions(rnd)
        if self.aae is not None:
            # detect/repair BEFORE the dispatch: a corrupt row caught
            # here never gossips outward (docs/RESILIENCE.md "Active
            # anti-entropy" — the detection-before-spread ordering)
            self.aae.on_round_start(rnd)
        mask = self.schedule.mask_at(rnd)
        self._account_duplicates(rnd, alive=mask)
        import jax

        frozen = None
        if self._needs_freeze():
            crash_rows = np.flatnonzero(self.crashed)
            frozen = {
                v: jax.tree_util.tree_map(
                    lambda x: x[crash_rows], self.rt.states[v]
                )
                for v in self.rt.var_ids
            }
        jmask = None if mask is None else self._device_mask(mask)
        if mode == "frontier":
            residual = self.rt.frontier_step(jmask)
        elif mode == "dense":
            residual = self.rt.step(jmask)
        else:
            raise ValueError(f"unknown mode {mode!r} (dense | frontier)")
        if frozen is not None:
            # a down replica executes nothing: local sweeps that moved
            # its row are rolled back (gossip cannot have — every edge
            # touching it is masked)
            idx = np.flatnonzero(self.crashed)
            for v in self.rt.var_ids:
                self.rt.states[v] = jax.tree_util.tree_map(
                    lambda x, fr: x.at[idx].set(fr),
                    self.rt.states[v], frozen[v],
                )
        self._emit_round_gauges(mask)
        self.round += 1
        if self.aae is not None:
            # commit this round's TRACKED changes into the hash forest
            # so the next verify has a clean baseline (incremental: a
            # quiescent round costs nothing)
            self.aae.on_round_end(rnd)
        return residual

    def _device_mask(self, mask):
        """One device transfer per DISTINCT host mask, keyed by OBJECT
        IDENTITY (the schedule returns the same array across a stable
        fault window — the identity the frontier mask-tagging keys on).
        The cache entry holds the host array itself: ``id()`` alone is
        unsound, because a freed mask's address (and so its id) is
        reused by the next allocation, and a stale hit would dispatch
        the WRONG mask — the entry's stored reference both pins the id
        and lets the hit verify ``is`` before trusting it."""
        key = ("mask", id(mask))
        cached = self._fused_cache.get(key)
        if cached is not None and cached[0] is mask:
            return cached[1]
        import jax.numpy as jnp

        # bound the cache: masks churn per round under flaky links
        for k in [k for k in self._fused_cache if k[0] == "mask"][:-8]:
            del self._fused_cache[k]
        dev = jnp.asarray(mask)
        self._fused_cache[key] = (mask, dev)
        return dev

    def fused_steps(self, n_rounds: int) -> list:
        """Run ``n_rounds`` chaos rounds in ONE device dispatch: the
        window's per-round masks stack into a traced ``bool[T, R, K]``
        operand and the runtime's full step (sweep + gossip + residual)
        runs under ``lax.fori_loop`` — the chaos twin of
        ``ReplicatedRuntime.fused_steps``, amortizing dispatch the same
        way. The window must contain no crash/restore action (those
        need host-side row surgery; :meth:`soak` splits windows at
        action rounds) and no live crash freeze with dataflow edges.
        Returns the per-round residual totals (host-synced once)."""
        import jax
        import jax.numpy as jnp

        if n_rounds < 1:
            raise ValueError("n_rounds must be >= 1")
        nxt = self.schedule.next_action_round(self.round - 1)
        if nxt is not None and nxt < self.round + n_rounds:
            raise RuntimeError(
                f"fused chaos window [{self.round}, "
                f"{self.round + n_rounds}) crosses a crash/restore "
                f"action at round {nxt} — split the window there"
            )
        if self._needs_freeze():
            raise RuntimeError(
                "fused chaos windows cannot freeze crashed rows around "
                "dataflow sweeps — step per round while replicas are "
                "down on a graph-carrying runtime"
            )
        rt = self.rt
        tables = rt._ensure_step()
        # per-round masks invalidate row knowledge wholesale (the
        # conservative opaque-block rule); sync against a sentinel so
        # the degrade happens ONCE here, not per cached mask identity
        rt._frontier_sync_mask(self)
        masks = self.schedule.masks(self.round, self.round + n_rounds)
        key = ("fused", n_rounds, rt.var_ids)
        fn = self._fused_cache.get(key)
        if fn is None:
            step = rt._step_pure
            n_vars = len(rt.var_ids)

            def fused(states, neighbors, masks_, tables_):
                def body(i, carry):
                    s, res = carry
                    out, res_vec = step(s, neighbors, masks_[i], tables_)
                    # PER-VAR per-round residual rows — the window's own
                    # flight record (T <= the flight ring bound is moot
                    # here: the carry is already per round, no modulo)
                    return out, res.at[i].set(res_vec.astype(jnp.int32))

                return jax.lax.fori_loop(
                    0, n_rounds, body,
                    (states, jnp.zeros((n_rounds, n_vars), jnp.int32)),
                )

            fn = jax.jit(fused)
            self._fused_cache[key] = fn
        from ..utils.metrics import Timer

        with span("chaos.fused_window", rounds=n_rounds):
            with Timer() as t:
                rt.states, res = rt._run_step_fn(
                    fn, jnp.asarray(masks), tables
                )
        res = np.asarray(res)  # [T, V] per-round per-var residuals
        totals = res.sum(axis=1)
        # masks varied inside the block: even a zero tail only proves a
        # MASKED fixed point — degrade (the opaque-block rule)
        rt._frontier_after_opaque(False)
        rt.trace.record_round(int(totals[-1]), t.elapsed)
        rt._record_rounds(n_rounds)
        # flight drain: real per-round residual curve points for the
        # chaos window (quiescent=None — a masked zero round proves only
        # a MASKED fixed point) plus the exact ledger join tally
        joins = rt._drain_flight(
            "chaos_window", res, n_rounds, None, t.elapsed,
        )
        # ledger: the stacked-mask window is its own kernel family (the
        # bool[T,R,K] mask operand rides the dispatch; each window
        # length is its own compiled executable, hence the block key)
        rt._ledger_record_store("chaos_window", t.elapsed, n_rounds,
                                block=n_rounds, joins=joins)
        # per-round duplicate accounting from the masks ALREADY compiled
        # for the dispatch (no second mask_at pass); gauges emit once for
        # the window's final round — intermediate per-round values could
        # never be observed before control returns anyway
        for t in range(n_rounds):
            self._account_duplicates(self.round, alive=masks[t])
            self.round += 1
        self._emit_round_gauges(masks[-1])
        if self.aae is not None:
            # the opaque block degraded every var to all-dirty: one
            # commit refresh keeps the forest's baseline current
            self.aae.on_round_end(self.round - 1)
        return totals.tolist()

    # -- degraded reads + read-repair -----------------------------------------
    def live_replicas(self) -> np.ndarray:
        return np.flatnonzero(~self.crashed)

    def _reachable_live(self, coordinator: int,
                        rnd: "int | None" = None) -> np.ndarray:
        """``bool[R]``: live replicas the coordinator can actually REACH
        over links alive under the round-``rnd`` mask (default: the
        CURRENT round; chaos masks are pair-symmetric, so this is
        undirected connectivity over the neighbor table's live pairs).
        A quorum must come from here — a host-side read spanning a
        partition would be a side channel that 'heals' through the very
        cut the nemesis installed. Callers acting BETWEEN rounds (the
        serving front-end's write-ack replication) pass the last
        EXECUTED round: the upcoming round's mask already isolates a
        replica whose crash has not happened yet, which is one round in
        the future of everything ``self.crashed`` reports."""
        live = ~self.crashed
        mask = self.schedule.mask_at(
            self.round if rnd is None else int(rnd)
        )
        nbrs = self.rt._host_neighbors
        if mask is None:
            return live
        alive_edge = np.asarray(mask, bool) & live[nbrs] & live[:, None]
        comp = np.zeros(self.rt.n_replicas, dtype=bool)
        comp[coordinator] = True
        for _ in range(self.rt.n_replicas):
            # expand over live pairs in BOTH roles: rows pulling a
            # component member, and rows a component member pulls
            fwd = (alive_edge & comp[nbrs]).any(axis=1)
            rev = np.zeros_like(comp)
            rev[nbrs[alive_edge & comp[:, None]]] = True
            new = comp | fwd | rev
            if (new == comp).all():
                break
            comp = new
        return comp & live

    def degraded_read(self, var_id: str, k: int = 2, repair: bool = True,
                      coordinator: "int | None" = None):
        """Quorum read from K LIVE, REACHABLE replicas — the reference's
        R=2 first-replies read (``src/lasp_read_fsm.erl:125-146``) under
        failures: crashed rows are excluded, and the quorum is drawn
        from the replicas the ``coordinator`` (default: the first live
        replica) can reach over links alive under the current round's
        mask — a partitioned coordinator answers from ITS side of the
        cut only, never through a host-side channel the mesh does not
        have. The first ``k`` such rows (deterministic preflist order)
        answer; their join is the returned value (a monotone lower
        bound of the coverage value).

        With ``repair=True`` (default) the read triggers READ-REPAIR as
        a masked partial join: the quorum's join merges back into
        exactly the rows read (``src/lasp_update_fsm.erl:189-216``
        finalize), those rows mark frontier-dirty, and the wire cost is
        accounted per row actually changed. Returns the decoded value."""
        live = self.live_replicas()
        if live.size == 0:
            raise ReplicaDownError(
                f"degraded_read({var_id!r}): every replica is down"
            )
        if coordinator is None:
            coordinator = int(live[0])
        elif self.crashed[coordinator]:
            raise ReplicaDownError(
                f"degraded_read({var_id!r}): coordinator {coordinator} "
                "is down"
            )
        reachable = np.flatnonzero(self._reachable_live(int(coordinator)))
        k = min(int(k), int(reachable.size))
        # coordinator-first preflist order (its own row always replies)
        picks = np.concatenate(
            [[int(coordinator)], reachable[reachable != int(coordinator)]]
        ).astype(np.int64)[:k]
        value = self.rt.quorum_value(var_id, picks)
        self.degraded_reads += 1
        counter(
            "chaos_degraded_reads_total",
            help="quorum reads answered from live replicas while the "
                 "population was degraded",
        ).inc()
        repaired = 0
        if repair:
            pop = self.rt._population(var_id)
            codec, spec = self.rt._mesh_meta(var_id)
            top = quorum_read(codec, spec, pop, picks)
            # the repair IS the quorum layer's masked-partial-join
            # primitive: join the quorum's top back into exactly the
            # rows read (changed rows mark frontier-dirty there)
            repaired = self.rt.join_rows(var_id, picks, top)
            if repaired:
                bytes_ = rows_traffic_bytes(pop, repaired)
                self.repair_bytes += bytes_
                self.repaired_rows += repaired
                counter(
                    "chaos_repair_bytes_total",
                    help="estimated bytes moved by degraded-read "
                         "read-repair partial joins",
                ).inc(bytes_)
        tel_events.emit(
            "chaos", var=var_id, action="degraded_read",
            quorum=[int(p) for p in picks], repaired_rows=repaired,
        )
        return value

    def write_at(self, replica: int, var_id: str, op: tuple, actor) -> None:
        """``update_at`` with availability semantics: a write routed to a
        crashed replica is REFUSED (the preflist would have routed
        around it; the simulation surfaces the decision)."""
        if self.crashed[replica]:
            raise ReplicaDownError(
                f"replica {replica} is down; route the write to a live "
                f"replica ({self.live_replicas()[:4].tolist()}...)"
            )
        self.rt.update_at(replica, var_id, op, actor)

    def write_batch(self, var_id: str, ops) -> None:
        """``update_batch`` with availability semantics — the batched
        twin of :meth:`write_at`, bit-identical to a per-op ``write_at``
        loop: the ops BEFORE the first one targeting a crashed replica
        apply (through the grouped ingest arm, ``mesh.ingest``), the
        refused op raises :class:`ReplicaDownError` with nothing of
        itself or its suffix applied."""
        ops = list(ops)
        down = next(
            (k for k, (r, _op, _a) in enumerate(ops)
             if self.crashed[int(r)]),
            None,
        )
        if down is None:
            self.rt.update_batch(var_id, ops)
            return
        if down:
            self.rt.update_batch(var_id, ops[:down])
        replica = int(ops[down][0])
        err = ReplicaDownError(
            f"replica {replica} is down; route the write to a live "
            f"replica ({self.live_replicas()[:4].tolist()}...)"
        )
        err.batch_index = down
        raise err

    # -- the soak driver ------------------------------------------------------
    def soak(self, max_rounds: int = 4096, mode: str = "dense",
             block: int = 1,
             reads_per_round: int = 0, read_var: "str | None" = None,
             read_quorum: int = 2) -> dict:
        """Run the WHOLE timeline and measure recovery: rounds execute
        (optionally issuing ``reads_per_round`` degraded reads against
        ``read_var`` while faults are active) until every fault has
        cleared AND the population quiesces. ``block > 1`` runs
        action-free windows through :meth:`fused_steps` (one dispatch
        per window) on runtimes without graphs/triggers.

        Returns the soak report: ``rounds``, ``rounds_to_heal`` (rounds
        past the schedule horizon to quiescence — the recovery metric),
        ``degraded_reads`` / ``repair_bytes`` / ``repaired_rows``,
        ``duplicates_suppressed``, ``crashes`` / ``restores``, and
        ``healed`` (no replica left down). The report also lands in the
        ConvergenceMonitor's ``chaos`` health section and the
        ``chaos_rounds_to_heal`` gauge."""
        horizon = self.schedule.horizon
        residual = -1
        with span("chaos.soak", mode=mode, horizon=horizon):
            while self.round < max_rounds:
                rnd = self.round
                in_window = rnd < horizon
                can_fuse = (
                    block > 1
                    and mode == "dense"
                    and not (self.rt.graph.edges or self.rt._triggers)
                    and not (reads_per_round and in_window)
                )
                nxt = self.schedule.next_action_round(rnd - 1)
                if can_fuse and (nxt is None or nxt > rnd):
                    width = block if nxt is None else min(block, nxt - rnd)
                    # actions take effect at round start: a window may
                    # not even BEGIN on an action or injection round
                    if not self.schedule.actions_at(rnd) and not (
                        self.schedule.corruptions_at(rnd)
                    ):
                        res = self.fused_steps(width)
                        residual = res[-1]
                        if residual == 0 and self.round > horizon:
                            break
                        continue
                residual = self.step(mode=mode)
                if reads_per_round and in_window and read_var is not None:
                    for _ in range(reads_per_round):
                        self.degraded_read(read_var, k=read_quorum)
                if residual == 0 and self.round > horizon:
                    break
            else:
                raise RuntimeError(
                    f"chaos soak did not quiesce within {max_rounds} "
                    "rounds"
                )
        healed = not bool(self.crashed.any())
        rounds_to_heal = max(0, self.round - horizon)
        gauge(
            "chaos_rounds_to_heal",
            help="rounds from the last fault clearing to quiescence in "
                 "the latest chaos soak",
        ).set(rounds_to_heal)
        report = {
            "rounds": self.round,
            "horizon": horizon,
            "rounds_to_heal": rounds_to_heal,
            "healed": healed,
            "residual": int(residual),
            "crashes": self.crashes,
            "restores": self.restores,
            "degraded_reads": self.degraded_reads,
            "repaired_rows": self.repaired_rows,
            "repair_bytes": self.repair_bytes,
            "duplicates_suppressed": self.duplicates_suppressed,
        }
        get_monitor().observe_chaos(**report)
        tel_events.emit("chaos", action="soak_done", **report)
        return report
