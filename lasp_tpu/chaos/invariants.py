"""Convergence-under-failure invariants — the harness the acceptance
criteria run.

Three properties must hold under EVERY schedule (they are the CRDT
correctness story restated as machine-checked invariants):

1. **per-replica monotone inflation** — every live replica row only
   moves UP the lattice, round over round (``merge(prev, new) == new``).
   The single deliberate exception is a crash-restore reseed (the row
   restarts at bottom / a checkpoint row), which the engine reports via
   ``ChaosRuntime.last_restored`` and the check exempts for that round;
2. **post-heal convergence to the fault-free fixed point** — after the
   schedule's horizon, the population quiesces to a state BIT-IDENTICAL
   to a twin run that never saw a fault: deterministic dataflow survives
   chaos (faults may delay convergence, never change its destination);
3. **replay determinism** — the same ``(seed, schedule)`` replays to
   identical per-round state fingerprints: chaos is an experiment you
   can re-run, bisect, and regress.

Property 2 subsumes the no-resurrection rule for observed-remove types
(a removed OR-Set/OR-SWOT dot resurrected across crash/restore would
make the healed state differ from the fault-free one), and
:func:`check_no_resurrection` additionally asserts it directly against a
caller-supplied removed-terms set, so a workload can pin the claim by
name instead of by bit-equality."""

from __future__ import annotations

import hashlib

import numpy as np

from .engine import ChaosRuntime


class InvariantViolation(AssertionError):
    """A chaos invariant failed; the message names the property, the
    variable, and the offending rows/round."""


def snapshot_states(rt) -> dict:
    """Host copies of every variable's population state."""
    import jax

    return {
        v: jax.tree_util.tree_map(np.asarray, rt.states[v])
        for v in rt.var_ids
    }


def states_equal(a: dict, b: dict) -> bool:
    import jax

    if set(a) != set(b):
        return False
    for v in a:
        same = jax.tree_util.tree_map(
            lambda x, y: bool(np.array_equal(x, y)), a[v], b[v]
        )
        if not all(jax.tree_util.tree_leaves(same)):
            return False
    return True


def fingerprint(states: dict) -> str:
    """Order-stable content hash of a population snapshot — the replay
    determinism unit (two runs match iff every leaf matches bit-wise)."""
    import jax

    h = hashlib.sha256()
    for v in sorted(states, key=str):
        h.update(repr(v).encode())
        for leaf in jax.tree_util.tree_leaves(states[v]):
            arr = np.asarray(leaf)
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
    return h.hexdigest()


def check_inflation(rt, prev: dict, exempt_rows=()) -> None:
    """Assert every replica row inflated (``new >= prev`` in lattice
    order: ``merge(prev, new) == new``) since the ``prev`` snapshot,
    for every variable — rows in ``exempt_rows`` (a restore's reseed)
    excepted. Raises :class:`InvariantViolation`."""
    import jax

    exempt = np.zeros(rt.n_replicas, dtype=bool)
    if len(exempt_rows):
        exempt[np.asarray(list(exempt_rows), dtype=np.int64)] = True
    for v in rt.var_ids:
        codec, spec = rt._mesh_meta(v)
        new = rt.states[v]
        ok = np.asarray(
            jax.vmap(
                lambda p, n: codec.equal(spec, codec.merge(spec, p, n), n)
            )(prev[v], new)
        )
        bad = np.flatnonzero(~ok & ~exempt)
        if bad.size:
            raise InvariantViolation(
                f"monotone-inflation violated for {v!r} at replica rows "
                f"{bad[:8].tolist()}: a round moved state DOWN the "
                "lattice outside a crash-restore reseed"
            )


def check_no_resurrection(rt, var_id: str, removed_terms) -> None:
    """Assert no removed element came back: the population's coverage
    value must be disjoint from ``removed_terms`` — the observed-remove
    guarantee across crash/restore (a reseeded row must not resurrect a
    tombstoned dot it once carried)."""
    value = rt.coverage_value(var_id)
    back = set(removed_terms) & set(value)
    if back:
        raise InvariantViolation(
            f"resurrection in {var_id!r}: removed element(s) "
            f"{sorted(map(repr, back))[:4]} reappeared after "
            "crash/restore"
        )


def check_no_write_lost(rt, acked_terms) -> None:
    """Assert no acknowledged write was lost: every term a client was
    told is durable (``acked_terms``: ``{var_id: terms}`` — the
    ``QuorumRuntime.acked_terms`` witness set) must appear in the
    variable's coverage value. This is the contract hinted handoff
    upholds across crash→restore: a put acked at W=2 whose ack replicas
    all crash and reseed from the lattice bottom would otherwise vanish
    entirely (the rolling-crash nemesis's signature data loss)."""
    for v, terms in acked_terms.items():
        value = rt.coverage_value(v)
        lost = set(terms) - set(value)
        if lost:
            raise InvariantViolation(
                f"acknowledged write(s) lost in {v!r}: "
                f"{sorted(map(repr, lost))[:4]} were acked at the client "
                "quorum but are absent from the coverage value after "
                "heal — hinted handoff failed its contract"
            )


def run_quorum_harness(build, schedule, *, writes, reads=(),
                       n: int = 3, r: int = 2, w: int = 2,
                       timeout: int = 4, retries: int = 2,
                       engine: str = "batched", mode: str = "dense",
                       hints_path: "str | None" = None,
                       max_rounds: int = 512, replay: bool = True) -> dict:
    """The quorum-coordination invariant suite: drive a put/get workload
    through a fault timeline and assert NO ACKNOWLEDGED WRITE IS LOST.

    ``build()`` constructs a fresh, identically-seeded
    ``ReplicatedRuntime`` (the ``run_harness`` contract). ``writes`` is
    a list of ``(round, var_id, op, actor, coordinator)`` — each put is
    submitted to the quorum engine just before that round executes;
    ``reads`` likewise ``(round, var_id, coordinator)`` degraded gets.
    The harness drains the batch past the schedule horizon to
    quiescence, then checks:

    - every fault healed and every submitted put resolved (an acked put
      may never be un-acked; a failed put is REPORTED, not lost — only
      ACKED terms enter the witness set);
    - :func:`check_no_write_lost` against the engine's acked-terms
      witness set (the hinted-handoff contract);
    - with ``replay=True``, a second identical run produces an
      identical protocol trace and final fingerprint (coordination is
      as replayable as the chaos underneath it).

    Returns the merged report (engine report + soak counters +
    ``acked``/``failed_puts`` counts)."""
    from ..quorum import HintLog, QuorumRuntime
    from .engine import ChaosRuntime

    def one_run():
        rt = build()
        ch = ChaosRuntime(rt, schedule)
        hints = HintLog(hints_path)
        # every run starts from an EMPTY log: the replay run must not
        # inherit the first run's fsync'd records (their handoff joins
        # would change the trace), nor run 1 a prior process's — the
        # harness owns the path for the duration of the check
        hints.prune()
        qr = QuorumRuntime(ch, n=n, r=r, w=w, timeout=timeout,
                           retries=retries, engine=engine, hints=hints,
                           mode=mode)
        pending = sorted(writes, key=lambda x: (x[0],))
        pending_reads = sorted(reads, key=lambda x: (x[0],))
        rids = []
        while (qr.inflight or pending or pending_reads
               or ch.round <= schedule.horizon):
            if ch.round >= max_rounds:
                raise InvariantViolation(
                    f"quorum harness did not drain within {max_rounds} "
                    f"rounds ({qr.inflight} in flight)"
                )
            while pending and pending[0][0] <= ch.round:
                _rnd, var, op, actor, coord = pending.pop(0)
                rids.append(qr.submit_put(var, op, actor, coord))
            while pending_reads and pending_reads[0][0] <= ch.round:
                _rnd, var, coord = pending_reads.pop(0)
                qr.submit_get(var, coord, degraded=True)
            qr.step()
        # post-drain anti-entropy to the fixed point (no faults remain):
        # the coverage reads below must judge the HEALED population
        rt.run_to_convergence(max_rounds=max_rounds)
        return rt, ch, qr, rids

    rt, ch, qr, rids = one_run()
    if ch.crashed.any():
        raise InvariantViolation(
            "quorum harness ended with replicas still down — the "
            "schedule must heal within its horizon"
        )
    unresolved = [
        rid for rid in rids
        if qr.result(rid, raise_on_error=False)["status"]
        not in ("done", "failed")
    ]
    if unresolved:
        raise InvariantViolation(
            f"puts {unresolved[:4]} never resolved (done/failed) after "
            "the drain — the FSM leaked an in-flight request"
        )
    check_no_write_lost(rt, qr.acked_terms)
    report = qr.report()
    report.update({
        "acked_terms": {
            str(v): len(ts) for v, ts in qr.acked_terms.items()
        },
        "rounds": ch.round,
        "healed": True,
        "no_write_lost": True,
    })
    if replay:
        rt2, _ch2, qr2, _ = one_run()
        if qr.trace != qr2.trace:
            first = next(
                (i for i, (a, b) in enumerate(zip(qr.trace, qr2.trace))
                 if a != b),
                min(len(qr.trace), len(qr2.trace)),
            )
            raise InvariantViolation(
                f"quorum replay diverged at trace entry {first}: the "
                "same (seed, schedule, submits) must replay to an "
                "identical protocol trace"
            )
        if fingerprint(snapshot_states(rt)) != fingerprint(
            snapshot_states(rt2)
        ):
            raise InvariantViolation(
                "quorum replay reached a different final state"
            )
        report["replay_identical"] = True
    return report


def check_corruption_detected_and_repaired(rt, chaos, scrubber,
                                           free_states: dict,
                                           detect_within: int = 1) -> dict:
    """The active-anti-entropy invariant (docs/RESILIENCE.md "Active
    anti-entropy"): judged over a FINISHED corruption soak,

    1. **detected** — every injected corruption (the engine's
       ``injected_corruptions`` ground truth) has a detection in the
       scrubber's ledger within ``detect_within`` rounds of injection;
    2. **localized exactly** — every detection names an injected
       (var, row): zero false positives (a detector that cried wolf on
       healthy rows would make repair itself the corruption vector);
    3. **repaired** — no repair left pending, and every detected
       (var, row) has an incident record (the quorum overwrite ran);
    4. **bit-equal** — the healed population equals the fault-free
       twin's fixed point, leaf for leaf.

    Returns the per-injection detection latencies (rounds)."""
    injected = chaos.injected_corruptions
    detected = scrubber.detected
    latencies = []
    for rec in injected:
        hits = [
            d for d in detected
            if d["var"] == rec["var"] and d["row"] == rec["row"]
            and rec["round"] <= d["round"]
            <= rec["round"] + detect_within
        ]
        if not hits:
            raise InvariantViolation(
                f"corruption UNDETECTED: {rec['kind']} at "
                f"({rec['var']!r}, row {rec['row']}) round "
                f"{rec['round']} has no detection within "
                f"{detect_within} round(s) — if the scrub cadence is "
                "wider than 1, a legit change to the row between "
                "scrubs commits (launders) the corruption into the "
                "hash baseline; see docs/RESILIENCE.md 'Active "
                "anti-entropy'"
            )
        latencies.append(
            min(d["round"] for d in hits) - rec["round"]
        )
    injected_keys = {(r["var"], r["row"]) for r in injected}
    for d in detected:
        if (d["var"], d["row"]) in injected_keys:
            continue
        if (
            d["source"] == "join_fixed_point"
            and (d["var"], d.get("pair")) in injected_keys
        ):
            # a still-diverging-after-join PAIR flags both endpoints
            # (which one is broken is unknowable from hashes alone);
            # localization is exact at pair granularity when the
            # partner row was the injected one
            continue
        raise InvariantViolation(
            f"corruption detector FALSE POSITIVE: flagged "
            f"({d['var']!r}, row {d['row']}) at round "
            f"{d['round']} ({d['source']}) but nothing was "
            "injected there — localization must be exact"
        )
    if scrubber.pending:
        raise InvariantViolation(
            f"corruption repair left pending: {sorted(scrubber.pending)}"
        )
    incident_keys = {(i["var"], i["row"]) for i in scrubber.incidents}
    missing = {(d["var"], d["row"]) for d in detected} - incident_keys
    if missing:
        raise InvariantViolation(
            f"detections never repaired (no incident record): "
            f"{sorted(missing)[:4]}"
        )
    if not states_equal(snapshot_states(rt), free_states):
        raise InvariantViolation(
            "post-repair fixed point differs from the fault-free "
            "twin's: a corruption survived detection/repair (or the "
            "repair destroyed state only the corrupt row held — see "
            "the fault-model note on sole-copy writes)"
        )
    return {"detection_latency_rounds": latencies}


def run_aae_harness(build, schedule, *, scrub_every: int = 1,
                    detect_within: "int | None" = None,
                    seg_size: int = 8, quorum: int = 3,
                    mode: str = "dense", max_rounds: int = 512,
                    replay: bool = True) -> dict:
    """The corruption-drill harness: drive a workload through a
    corruption-carrying fault timeline with an
    :class:`~lasp_tpu.aae.AAEScrubber` attached, then assert
    :func:`check_corruption_detected_and_repaired` (detection within
    ``detect_within`` rounds — default the scrub cadence — exact
    localization, full repair, twin bit-equality) and, with
    ``replay=True``, that a second identical run reproduces the
    detection ledger and final fingerprint bit-for-bit.

    ``build()`` is the usual fresh-identically-seeded-runtime builder
    (the ``run_harness`` contract). Returns the merged report:
    detection latencies, repair traffic vs a full-state resync, hash
    work by mode, incident count."""
    from ..aae import AAEScrubber

    if scrub_every > 1 and mode != "frontier":
        # dense stepping marks EVERY row dirty each active round (the
        # conservative degrade), so any between-scrub gossip commits a
        # corrupt row's hash as the new baseline — laundered before the
        # next verify could see it. The detection-within-cadence
        # guarantee this harness asserts therefore only exists at
        # cadence 1 under dense stepping; frontier's exact dirty
        # tracking extends it to rows untouched between scrubs
        # (docs/RESILIENCE.md "Active anti-entropy").
        raise ValueError(
            f"scrub_every={scrub_every} with mode={mode!r} cannot "
            "uphold the detection guarantee (dense all-dirty marks "
            "launder corruption between scrubs) — use scrub_every=1, "
            "or mode='frontier' for wider cadences"
        )
    if detect_within is None:
        detect_within = int(scrub_every)
    rt_free = build()
    free_rounds = rt_free.run_to_convergence(max_rounds=max_rounds)
    free_states = snapshot_states(rt_free)
    del rt_free

    def one_run():
        rt = build()
        ch = ChaosRuntime(rt, schedule)
        sc = AAEScrubber(ch, scrub_every=scrub_every,
                         seg_size=seg_size, quorum=quorum)
        while ch.round < max_rounds:
            residual = ch.step(mode=mode)
            if (
                residual == 0
                and ch.round > schedule.horizon
                and not sc.pending
            ):
                break
        else:
            raise InvariantViolation(
                f"AAE soak did not quiesce within {max_rounds} rounds "
                f"({len(sc.pending)} repairs pending)"
            )
        # closing scrub: verify the final population (a corruption
        # landing on the very last faulted round must still be caught)
        sc.scrub(ch.round)
        rt.run_to_convergence(max_rounds=max_rounds)
        return rt, ch, sc

    rt1, ch1, sc1 = one_run()
    checked = check_corruption_detected_and_repaired(
        rt1, ch1, sc1, free_states, detect_within=detect_within
    )
    report = sc1.report()
    report.update(checked)
    report.update({
        "injected": len(ch1.injected_corruptions),
        "injected_by_kind": {
            k: sum(1 for r in ch1.injected_corruptions
                   if r["kind"] == k)
            for k in {r["kind"] for r in ch1.injected_corruptions}
        },
        "rounds": ch1.round,
        "fault_free_rounds": free_rounds,
        "healed": not bool(ch1.crashed.any()),
        "bit_identical_to_fault_free": True,
        "detected_and_repaired": True,
    })
    if replay:
        rt2, ch2, sc2 = one_run()
        if sc1.detected != sc2.detected or (
            ch1.injected_corruptions != ch2.injected_corruptions
        ):
            raise InvariantViolation(
                "AAE replay diverged: the same (seed, schedule) must "
                "reproduce the injection and detection ledgers exactly"
            )
        if fingerprint(snapshot_states(rt1)) != fingerprint(
            snapshot_states(rt2)
        ):
            raise InvariantViolation(
                "AAE replay reached a different final state"
            )
        report["replay_identical"] = True
    return report


def run_harness(build, schedule, mode: str = "dense",
                max_rounds: int = 512, replay: bool = True,
                removed_terms: "dict | None" = None,
                checkpoint: "str | None" = None) -> dict:
    """Execute the full invariant suite for one workload × schedule ×
    scheduler mode.

    ``build()`` constructs a fresh, identically-seeded
    ``ReplicatedRuntime`` (same store declarations, same client writes,
    same topology — the schedule must have been compiled against that
    topology). The harness then runs:

    - a FAULT-FREE twin to its fixed point (the destination states);
    - the CHAOS run, checking monotone inflation every round and the
      healed fixed point's bit-equality with the twin;
    - with ``replay=True``, a second chaos run, checking per-round
      fingerprint equality (determinism);
    - with ``removed_terms`` (``{var_id: terms}``), the direct
      no-resurrection assertion per variable.

    Returns a report dict (rounds, rounds_to_heal, fingerprints, soak
    counters); raises :class:`InvariantViolation` on any failure."""
    rt_free = build()
    free_rounds = rt_free.run_to_convergence(
        max_rounds=max_rounds, mode=mode if mode == "frontier" else "dense"
    )
    free_states = snapshot_states(rt_free)
    del rt_free

    def chaos_run():
        rt = build()
        ch = ChaosRuntime(rt, schedule, checkpoint=checkpoint)
        prev = snapshot_states(rt)
        fps = []
        while ch.round < max_rounds:
            residual = ch.step(mode=mode)
            check_inflation(rt, prev, exempt_rows=ch.last_restored)
            prev = snapshot_states(rt)
            fps.append(fingerprint(prev))
            if residual == 0 and ch.round > schedule.horizon:
                break
        else:
            raise InvariantViolation(
                f"chaos run did not quiesce within {max_rounds} rounds "
                f"(mode={mode!r})"
            )
        return rt, ch, fps

    rt1, ch1, fps1 = chaos_run()
    if not states_equal(snapshot_states(rt1), free_states):
        raise InvariantViolation(
            "post-heal fixed point differs from the fault-free run's "
            f"(mode={mode!r}): chaos changed the destination, not just "
            "the journey"
        )
    if removed_terms:
        for v, terms in removed_terms.items():
            check_no_resurrection(rt1, v, terms)
    from ..telemetry.convergence import get_monitor

    report = {
        "mode": mode,
        "fault_free_rounds": free_rounds,
        "chaos_rounds": ch1.round,
        "rounds_to_heal": max(0, ch1.round - schedule.horizon),
        "healed": not bool(ch1.crashed.any()),
        "crashes": ch1.crashes,
        "restores": ch1.restores,
        "final_fingerprint": fps1[-1],
        "bit_identical_to_fault_free": True,
    }
    if replay:
        _rt2, _ch2, fps2 = chaos_run()
        if fps1 != fps2:
            first = next(
                (i for i, (a, b) in enumerate(zip(fps1, fps2)) if a != b),
                min(len(fps1), len(fps2)),
            )
            raise InvariantViolation(
                f"replay diverged at round {first} (mode={mode!r}): the "
                "same (seed, schedule) must replay to identical "
                "per-round states"
            )
        report["replay_identical"] = True
    # the observatory's resilience section: invariant runs feed the same
    # health surface soaks do (the {health} verb's "chaos" key)
    get_monitor().observe_chaos(
        rounds_to_heal=report["rounds_to_heal"], healed=report["healed"],
        crashes=report["crashes"], restores=report["restores"],
        invariants_ok=True,
    )
    return report
