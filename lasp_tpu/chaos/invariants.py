"""Convergence-under-failure invariants — the harness the acceptance
criteria run.

Three properties must hold under EVERY schedule (they are the CRDT
correctness story restated as machine-checked invariants):

1. **per-replica monotone inflation** — every live replica row only
   moves UP the lattice, round over round (``merge(prev, new) == new``).
   The single deliberate exception is a crash-restore reseed (the row
   restarts at bottom / a checkpoint row), which the engine reports via
   ``ChaosRuntime.last_restored`` and the check exempts for that round;
2. **post-heal convergence to the fault-free fixed point** — after the
   schedule's horizon, the population quiesces to a state BIT-IDENTICAL
   to a twin run that never saw a fault: deterministic dataflow survives
   chaos (faults may delay convergence, never change its destination);
3. **replay determinism** — the same ``(seed, schedule)`` replays to
   identical per-round state fingerprints: chaos is an experiment you
   can re-run, bisect, and regress.

Property 2 subsumes the no-resurrection rule for observed-remove types
(a removed OR-Set/OR-SWOT dot resurrected across crash/restore would
make the healed state differ from the fault-free one), and
:func:`check_no_resurrection` additionally asserts it directly against a
caller-supplied removed-terms set, so a workload can pin the claim by
name instead of by bit-equality."""

from __future__ import annotations

import hashlib

import numpy as np

from .engine import ChaosRuntime


class InvariantViolation(AssertionError):
    """A chaos invariant failed; the message names the property, the
    variable, and the offending rows/round."""


def snapshot_states(rt) -> dict:
    """Host copies of every variable's population state."""
    import jax

    return {
        v: jax.tree_util.tree_map(np.asarray, rt.states[v])
        for v in rt.var_ids
    }


def states_equal(a: dict, b: dict) -> bool:
    import jax

    if set(a) != set(b):
        return False
    for v in a:
        same = jax.tree_util.tree_map(
            lambda x, y: bool(np.array_equal(x, y)), a[v], b[v]
        )
        if not all(jax.tree_util.tree_leaves(same)):
            return False
    return True


def fingerprint(states: dict) -> str:
    """Order-stable content hash of a population snapshot — the replay
    determinism unit (two runs match iff every leaf matches bit-wise)."""
    import jax

    h = hashlib.sha256()
    for v in sorted(states, key=str):
        h.update(repr(v).encode())
        for leaf in jax.tree_util.tree_leaves(states[v]):
            arr = np.asarray(leaf)
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
    return h.hexdigest()


def check_inflation(rt, prev: dict, exempt_rows=()) -> None:
    """Assert every replica row inflated (``new >= prev`` in lattice
    order: ``merge(prev, new) == new``) since the ``prev`` snapshot,
    for every variable — rows in ``exempt_rows`` (a restore's reseed)
    excepted. Raises :class:`InvariantViolation`."""
    import jax

    exempt = np.zeros(rt.n_replicas, dtype=bool)
    if len(exempt_rows):
        exempt[np.asarray(list(exempt_rows), dtype=np.int64)] = True
    for v in rt.var_ids:
        codec, spec = rt._mesh_meta(v)
        new = rt.states[v]
        ok = np.asarray(
            jax.vmap(
                lambda p, n: codec.equal(spec, codec.merge(spec, p, n), n)
            )(prev[v], new)
        )
        bad = np.flatnonzero(~ok & ~exempt)
        if bad.size:
            raise InvariantViolation(
                f"monotone-inflation violated for {v!r} at replica rows "
                f"{bad[:8].tolist()}: a round moved state DOWN the "
                "lattice outside a crash-restore reseed"
            )


def check_no_resurrection(rt, var_id: str, removed_terms) -> None:
    """Assert no removed element came back: the population's coverage
    value must be disjoint from ``removed_terms`` — the observed-remove
    guarantee across crash/restore (a reseeded row must not resurrect a
    tombstoned dot it once carried)."""
    value = rt.coverage_value(var_id)
    back = set(removed_terms) & set(value)
    if back:
        raise InvariantViolation(
            f"resurrection in {var_id!r}: removed element(s) "
            f"{sorted(map(repr, back))[:4]} reappeared after "
            "crash/restore"
        )


def run_harness(build, schedule, mode: str = "dense",
                max_rounds: int = 512, replay: bool = True,
                removed_terms: "dict | None" = None,
                checkpoint: "str | None" = None) -> dict:
    """Execute the full invariant suite for one workload × schedule ×
    scheduler mode.

    ``build()`` constructs a fresh, identically-seeded
    ``ReplicatedRuntime`` (same store declarations, same client writes,
    same topology — the schedule must have been compiled against that
    topology). The harness then runs:

    - a FAULT-FREE twin to its fixed point (the destination states);
    - the CHAOS run, checking monotone inflation every round and the
      healed fixed point's bit-equality with the twin;
    - with ``replay=True``, a second chaos run, checking per-round
      fingerprint equality (determinism);
    - with ``removed_terms`` (``{var_id: terms}``), the direct
      no-resurrection assertion per variable.

    Returns a report dict (rounds, rounds_to_heal, fingerprints, soak
    counters); raises :class:`InvariantViolation` on any failure."""
    rt_free = build()
    free_rounds = rt_free.run_to_convergence(
        max_rounds=max_rounds, mode=mode if mode == "frontier" else "dense"
    )
    free_states = snapshot_states(rt_free)
    del rt_free

    def chaos_run():
        rt = build()
        ch = ChaosRuntime(rt, schedule, checkpoint=checkpoint)
        prev = snapshot_states(rt)
        fps = []
        while ch.round < max_rounds:
            residual = ch.step(mode=mode)
            check_inflation(rt, prev, exempt_rows=ch.last_restored)
            prev = snapshot_states(rt)
            fps.append(fingerprint(prev))
            if residual == 0 and ch.round > schedule.horizon:
                break
        else:
            raise InvariantViolation(
                f"chaos run did not quiesce within {max_rounds} rounds "
                f"(mode={mode!r})"
            )
        return rt, ch, fps

    rt1, ch1, fps1 = chaos_run()
    if not states_equal(snapshot_states(rt1), free_states):
        raise InvariantViolation(
            "post-heal fixed point differs from the fault-free run's "
            f"(mode={mode!r}): chaos changed the destination, not just "
            "the journey"
        )
    if removed_terms:
        for v, terms in removed_terms.items():
            check_no_resurrection(rt1, v, terms)
    from ..telemetry.convergence import get_monitor

    report = {
        "mode": mode,
        "fault_free_rounds": free_rounds,
        "chaos_rounds": ch1.round,
        "rounds_to_heal": max(0, ch1.round - schedule.horizon),
        "healed": not bool(ch1.crashed.any()),
        "crashes": ch1.crashes,
        "restores": ch1.restores,
        "final_fingerprint": fps1[-1],
        "bit_identical_to_fault_free": True,
    }
    if replay:
        _rt2, _ch2, fps2 = chaos_run()
        if fps1 != fps2:
            first = next(
                (i for i, (a, b) in enumerate(zip(fps1, fps2)) if a != b),
                min(len(fps1), len(fps2)),
            )
            raise InvariantViolation(
                f"replay diverged at round {first} (mode={mode!r}): the "
                "same (seed, schedule) must replay to identical "
                "per-round states"
            )
        report["replay_identical"] = True
    # the observatory's resilience section: invariant runs feed the same
    # health surface soaks do (the {health} verb's "chaos" key)
    get_monitor().observe_chaos(
        rounds_to_heal=report["rounds_to_heal"], healed=report["healed"],
        crashes=report["crashes"], restores=report["restores"],
        invariants_ok=True,
    )
    return report
