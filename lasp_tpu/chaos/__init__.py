"""Chaos mesh: deterministic fault injection, crash/recovery, and
convergence-under-failure invariants.

The robustness half of the replication story (N-replica preflists with
R/W=2 quorums + read-repair, ``src/lasp_update_fsm.erl:174-216``),
rebuilt as three pieces:

- :mod:`.schedule` — declarative, seeded fault timelines
  (:class:`ChaosSchedule`, the event vocabulary, the
  :func:`nemesis` presets) that compile per round into the edge masks
  the existing gossip kernels already accept;
- :mod:`.engine` — :class:`ChaosRuntime`, wrapping a
  ``ReplicatedRuntime`` with crash/restore row surgery, degraded
  quorum reads + read-repair partial joins, and the measured
  :meth:`~ChaosRuntime.soak` driver;
- :mod:`.invariants` — the harness asserting monotone inflation,
  post-heal bit-equality with a fault-free run, replay determinism,
  and no tombstone resurrection.

Surfaces: ``lasp_tpu chaos`` (CLI soak verb), ``Session.nemesis``,
the ``chaos_heal`` bench scenario, and ``tools/chaos_smoke.py`` in
``make verify``. See docs/RESILIENCE.md.
"""

from .engine import ChaosRuntime, ReplicaDownError
from .invariants import (
    InvariantViolation,
    check_corruption_detected_and_repaired,
    check_inflation,
    check_no_resurrection,
    check_no_write_lost,
    fingerprint,
    run_aae_harness,
    run_harness,
    run_quorum_harness,
    snapshot_states,
    states_equal,
)
from .schedule import (
    CORRUPTION_KINDS,
    CORRUPTION_PRESETS,
    PRESETS,
    BitRot,
    ChaosSchedule,
    CorruptRows,
    Crash,
    DelayLinks,
    DuplicateLinks,
    FlakyLinks,
    Partition,
    Restore,
    SlowShard,
    nemesis,
)

__all__ = [
    "CORRUPTION_KINDS",
    "CORRUPTION_PRESETS",
    "PRESETS",
    "BitRot",
    "ChaosRuntime",
    "ChaosSchedule",
    "CorruptRows",
    "Crash",
    "DelayLinks",
    "DuplicateLinks",
    "FlakyLinks",
    "InvariantViolation",
    "Partition",
    "ReplicaDownError",
    "Restore",
    "SlowShard",
    "check_corruption_detected_and_repaired",
    "check_inflation",
    "check_no_resurrection",
    "check_no_write_lost",
    "fingerprint",
    "nemesis",
    "run_aae_harness",
    "run_harness",
    "run_quorum_harness",
    "snapshot_states",
    "states_equal",
]
