"""Declarative, deterministic fault timelines — the nemesis schedule.

The reference's failure story is implicit: riak_core N=3 preflists with
R/W=2 quorums survive a down vnode, and read-repair reconstructs it on
return (``src/lasp_update_fsm.erl:174-216``, ``src/lasp_vnode.erl:
454-472``). This module makes the *fault side* of that story explicit
and reproducible: a :class:`ChaosSchedule` is a timeline of fault events
(partitions, flaky/delayed/duplicated links, replica crash/restore,
slow-shard throttling) that COMPILES, per round, into exactly the
``edge_mask: bool[R, K]`` perturbation the existing gossip kernels
already accept (``mesh.gossip.gossip_round`` /
``gossip_round_rows`` / ``gossip_round_shift``, ``ops.fused``). No new
collective path exists for chaos — the DrJAX discipline (PAPERS.md,
arXiv:2403.07128): failure semantics expressed inside the traced
computation stay jit-friendly and bit-reproducible.

Determinism contract: every mask is a pure function of ``(seed,
schedule, round)`` — per-link randomness comes from a counter-based
hash over the ORDER-FREE link key (both directions of a pair draw the
same uniform), so every schedule is symmetric by construction and the
same ``(seed, schedule)`` replays to identical per-round masks on any
host (no RandomState stream ordering involved).

Fault semantics under CRDT gossip (why two of the classic nemeses are
mask-expressible at all):

- **delay**: pull-gossip state is monotone and join-idempotent, so a
  message delayed ``d`` rounds is SUBSUMED by the first later delivery
  — the peer's newer state contains everything the delayed frame
  carried. A delayed-delivery buffer that holds frames ``d`` rounds and
  then flushes is therefore observationally equal to masking the link
  for ``d`` rounds and letting the next pull through; ``DelayLinks``
  compiles to exactly that mask window.
- **duplication**: an idempotent join makes a duplicated delivery a
  literal no-op (``join(s, x, x) == join(s, x)``). ``DuplicateLinks``
  perturbs no mask; it exists so soaks COUNT the duplicates the
  protocol absorbed (``chaos_duplicate_deliveries_total``) — the
  at-least-once tolerance claim, measured instead of asserted.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Partition(NamedTuple):
    """Split the population into ``n_groups`` contiguous groups for
    rounds ``[start, stop)``: only intra-group links stay alive
    (``topology.partition_mask`` semantics, symmetric by construction).
    Healing = the window ending."""

    start: int
    stop: int
    n_groups: int = 2


class FlakyLinks(NamedTuple):
    """Per-round Bernoulli link loss in ``[start, stop)``: each LINK
    (order-free replica pair) independently drops with ``drop_rate``
    each round, both directions together. The draw is counter-based on
    ``(seed, link, round)`` — reproducible, stream-order-free."""

    start: int
    stop: int
    drop_rate: float = 0.2


class DelayLinks(NamedTuple):
    """Delayed delivery on a seeded ``frac`` subset of links for rounds
    ``[start, stop)``: an affected link's buffer flushes only every
    ``delay + 1`` rounds (mask-window compilation — see the module doc
    for why this equals a real delayed-delivery buffer under monotone
    idempotent joins)."""

    start: int
    stop: int
    frac: float = 0.3
    delay: int = 2


class DuplicateLinks(NamedTuple):
    """At-least-once delivery on a seeded ``frac`` subset of links:
    every delivery in the window arrives twice. A no-op under the
    idempotent join (the point) — compiled into accounting, not masks."""

    start: int
    stop: int
    frac: float = 0.3


class Crash(NamedTuple):
    """Replica ``replica`` fails-stop at the start of round ``at``:
    every link touching it dies (it neither contributes state nor
    pulls), its row freezes, and client writes to it are refused until
    a :class:`Restore`."""

    at: int
    replica: int


class Restore(NamedTuple):
    """Replica ``replica`` returns at the start of round ``at``, its row
    re-seeded from the lattice bottom (``source="bottom"``) or from a
    runtime checkpoint row (``source="checkpoint"`` — the engine's
    attached snapshot), then caught up by gossip (every frontier
    degrades to all-dirty: the hinted-handoff-style recovery)."""

    at: int
    replica: int
    source: str = "bottom"


class SlowShard(NamedTuple):
    """Throttle one contiguous shard block for rounds ``[start, stop)``:
    links touching the shard's rows (``shard_gossip.shard_rows``
    blocking) deliver only every ``period``-th round — a lagging device
    / oversubscribed host, not a failure."""

    start: int
    stop: int
    shard: int = 0
    n_shards: int = 4
    period: int = 3


#: silent-corruption mutation kinds (``docs/RESILIENCE.md`` fault
#: model): ``bitflip`` flips one state bit (a flipped exists-bit —
#: possibly INFLATIONARY: the one corruption class gossip would spread
#: outward), ``rollback`` halves a positive counter lane (counter
#: rollback — non-inflationary), ``truncate`` zeroes the tail half of
#: the row's last wire plane (truncated dot planes).
CORRUPTION_KINDS = ("bitflip", "rollback", "truncate")


class CorruptRows(NamedTuple):
    """SILENT corruption at the start of round ``at``: ``n_rows``
    seeded live replica rows of a seeded variable (``var`` None = drawn
    over the store) mutate per ``kind`` — directly in device state,
    bypassing every dirty-tracking path (that is the fault class:
    bit-rot, a bad kernel, a botched restore). Pure function of
    ``(seed, schedule, round)`` like every other event; no mask
    effect. Detection/repair is the AAE layer's job
    (``lasp_tpu.aae``) — without it, gossip happily joins the
    corruption outward."""

    at: int
    kind: str = "bitflip"
    n_rows: int = 1
    var: "str | None" = None


class BitRot(NamedTuple):
    """Windowed :class:`CorruptRows`: one seeded injection every
    ``every``-th round of ``[start, stop)`` — ambient media decay
    rather than a point event."""

    start: int
    stop: int
    every: int = 2
    kind: str = "bitflip"
    n_rows: int = 1
    var: "str | None" = None


#: event kinds with a [start, stop) activity window
_WINDOWED = (Partition, FlakyLinks, DelayLinks, DuplicateLinks,
             SlowShard, BitRot)


def _mix(keys: np.ndarray, salt: int) -> np.ndarray:
    """Counter-based uniform in [0, 1) per key — splitmix64-style
    finalizer, deterministic across hosts (no RandomState streams)."""
    x = keys.astype(np.uint64)
    x = x * np.uint64(0x9E3779B97F4A7C15) + np.uint64(salt & (2**64 - 1))
    x ^= x >> np.uint64(33)
    x = x * np.uint64(0xFF51AFD7ED558CCD)
    x ^= x >> np.uint64(33)
    return (x % np.uint64(1 << 53)).astype(np.float64) / float(1 << 53)


class ChaosSchedule:
    """A reproducible fault timeline over one population + topology.

    ``events`` is any iterable of the event tuples above; ``seed`` feeds
    every stochastic draw. The schedule is immutable and stateless apart
    from a content-keyed mask cache: rounds whose fault state is
    identical return the SAME mask object, so the frontier engine's
    identity-keyed mask tagging (``ReplicatedRuntime._frontier_sync_mask``)
    keeps its dirty-set knowledge across a stable fault window instead
    of degrading every round."""

    def __init__(self, n_replicas: int, neighbors, events=(), seed: int = 0):
        from ..mesh.topology import _pair_keys

        self.n_replicas = int(n_replicas)
        self.neighbors = np.asarray(neighbors)
        if (
            self.neighbors.ndim != 2
            or self.neighbors.shape[0] != self.n_replicas
        ):
            raise ValueError(
                f"neighbors must be [{n_replicas}, K], got "
                f"{self.neighbors.shape}"
            )
        self.seed = int(seed)
        self.events = tuple(events)
        for ev in self.events:
            if isinstance(ev, _WINDOWED):
                if ev.stop <= ev.start:
                    raise ValueError(f"empty fault window: {ev!r}")
                if isinstance(ev, BitRot):
                    if ev.kind not in CORRUPTION_KINDS:
                        raise ValueError(
                            f"{ev!r}: kind must be one of "
                            f"{CORRUPTION_KINDS}"
                        )
                    if ev.every < 1 or ev.n_rows < 1:
                        raise ValueError(
                            f"{ev!r}: every and n_rows must be >= 1"
                        )
            elif isinstance(ev, CorruptRows):
                if ev.kind not in CORRUPTION_KINDS:
                    raise ValueError(
                        f"{ev!r}: kind must be one of {CORRUPTION_KINDS}"
                    )
                if ev.n_rows < 1:
                    raise ValueError(f"{ev!r}: n_rows must be >= 1")
            elif isinstance(ev, (Crash, Restore)):
                if not 0 <= ev.replica < self.n_replicas:
                    raise ValueError(
                        f"{ev!r}: replica out of range for {n_replicas}"
                    )
                if isinstance(ev, Restore) and ev.source not in (
                    "bottom", "checkpoint",
                ):
                    raise ValueError(
                        f"{ev!r}: source must be 'bottom' or 'checkpoint'"
                    )
            else:
                raise TypeError(f"unknown chaos event {ev!r}")
        self._pair_keys = _pair_keys(self.neighbors)
        # validate crash/restore pairing ONCE: a restore without a
        # preceding crash (or a double crash) is a schedule bug that
        # would otherwise surface rounds later as a confusing freeze
        downs: set = set()
        for ev in self._actions_sorted():
            if isinstance(ev, Crash):
                if ev.replica in downs:
                    raise ValueError(
                        f"{ev!r}: replica already crashed and not restored"
                    )
                downs.add(ev.replica)
            elif isinstance(ev, Restore):
                if ev.replica not in downs:
                    raise ValueError(f"{ev!r}: replica is not crashed")
                downs.discard(ev.replica)
        #: first round with every fault cleared (windows closed, crashed
        #: replicas restored) — the heal point soaks measure recovery
        #: from. max() over an empty timeline = round 0 (no faults).
        horizon = 0
        for ev in self.events:
            horizon = max(
                horizon, ev.stop if isinstance(ev, _WINDOWED) else ev.at
            )
        self.horizon = horizon
        self._mask_cache: "tuple | None" = None  # (bytes, mask or None)

    # -- event queries --------------------------------------------------------
    def _actions_sorted(self):
        return sorted(
            (ev for ev in self.events if isinstance(ev, (Crash, Restore))),
            key=lambda ev: (ev.at, isinstance(ev, Crash)),
        )

    def actions_at(self, rnd: int) -> list:
        """Crash/Restore events taking effect at the START of ``rnd``
        (restores ordered before crashes, so a same-round
        restore-then-crash of different replicas resolves sanely)."""
        return [ev for ev in self._actions_sorted() if ev.at == rnd]

    def next_action_round(self, rnd: int) -> "int | None":
        """First round > ``rnd`` with a crash/restore action or a
        corruption injection (None when the timeline holds no further
        actions) — fused chaos windows must break there to process the
        action host-side."""
        future = [ev.at for ev in self.events
                  if isinstance(ev, (Crash, Restore)) and ev.at > rnd]
        for ev in self.events:
            if isinstance(ev, CorruptRows) and ev.at > rnd:
                future.append(ev.at)
            elif isinstance(ev, BitRot):
                if rnd < ev.start:
                    nxt = ev.start
                else:
                    k = (rnd - ev.start) // ev.every + 1
                    nxt = ev.start + k * ev.every
                if nxt < ev.stop:
                    future.append(nxt)
        return min(future) if future else None

    def corruptions_at(self, rnd: int) -> list:
        """Corruption injections due at the START of ``rnd``:
        ``[(event_index, event, shot), ...]`` where ``shot`` is the
        occurrence ordinal inside a :class:`BitRot` window (0 for point
        :class:`CorruptRows`) — the per-occurrence seed column."""
        out = []
        for i, ev in enumerate(self.events):
            if isinstance(ev, CorruptRows) and ev.at == rnd:
                out.append((i, ev, 0))
            elif (
                isinstance(ev, BitRot)
                and ev.start <= rnd < ev.stop
                and (rnd - ev.start) % ev.every == 0
            ):
                out.append((i, ev, (rnd - ev.start) // ev.every))
        return out

    def crashed_at(self, rnd: int) -> np.ndarray:
        """``bool[R]``: replicas down DURING round ``rnd`` (actions take
        effect at round start)."""
        down = np.zeros(self.n_replicas, dtype=bool)
        for ev in self._actions_sorted():
            if ev.at > rnd:
                break
            down[ev.replica] = isinstance(ev, Crash)
        return down

    def active_at(self, rnd: int) -> list:
        """Windowed fault events active during round ``rnd``."""
        return [
            ev for ev in self.events
            if isinstance(ev, _WINDOWED) and ev.start <= rnd < ev.stop
        ]

    def duplicate_links_at(self, rnd: int, alive=None) -> int:
        """How many LIVE directed edges deliver TWICE this round under
        active ``DuplicateLinks`` windows (the at-least-once accounting;
        idempotence makes the duplicates no-ops). Only edges that
        actually deliver count: dead links (this round's mask — pass
        ``alive`` when the caller already holds it to skip the rebuild),
        crashed endpoints, and structural self-edges deliver nothing and
        are excluded."""
        windows = [
            (i, ev) for i, ev in enumerate(self.events)
            if isinstance(ev, DuplicateLinks) and ev.start <= rnd < ev.stop
        ]
        if not windows:
            return 0
        if alive is None:
            alive = self.mask_at(rnd)
        delivering = (
            np.ones(self.neighbors.shape, dtype=bool)
            if alive is None
            else np.asarray(alive, dtype=bool)
        )
        r = np.arange(self.n_replicas, dtype=np.int64)[:, None]
        delivering = delivering & (self.neighbors != r)  # self-edges: no-op
        total = 0
        for i, ev in windows:
            u = _mix(self._pair_keys, self.seed * 1_000_003 + i * 7919)
            total += int(((u < ev.frac) & delivering).sum())
        return total

    # -- mask compilation -----------------------------------------------------
    def mask_at(self, rnd: int) -> "np.ndarray | None":
        """The edge-alive mask round ``rnd`` runs under: ``bool[R, K]``
        (True = alive), or None when no fault is active (the unmasked
        fast path). Symmetric by construction — every kill is keyed on
        the order-free link — and content-cached: consecutive rounds
        with identical fault state share ONE array object (the frontier
        mask-identity contract)."""
        from ..mesh.topology import symmetrize_edge_mask

        nbrs = self.neighbors
        R, K = nbrs.shape
        alive = np.ones((R, K), dtype=bool)
        any_fault = False
        for i, ev in enumerate(self.events):
            if not isinstance(ev, _WINDOWED) or not (
                ev.start <= rnd < ev.stop
            ):
                continue
            if isinstance(ev, Partition):
                group = (np.arange(R) * ev.n_groups) // R
                alive &= group[:, None] == group[nbrs]
                any_fault = True
            elif isinstance(ev, FlakyLinks):
                u = _mix(
                    self._pair_keys,
                    (self.seed * 1_000_003 + i * 7919) ^ (rnd * 2_654_435),
                )
                alive &= u >= ev.drop_rate
                any_fault = True
            elif isinstance(ev, DelayLinks):
                u = _mix(self._pair_keys, self.seed * 1_000_003 + i * 7919)
                affected = u < ev.frac
                # the buffered link flushes only every delay+1 rounds
                if (rnd - ev.start) % (ev.delay + 1) != ev.delay:
                    alive &= ~affected
                    any_fault = True
            elif isinstance(ev, SlowShard):
                if (rnd - ev.start) % ev.period != 0:
                    from ..mesh.shard_gossip import shard_rows

                    rows = shard_rows(R, ev.n_shards, ev.shard)
                    touched = np.zeros(R, dtype=bool)
                    touched[rows] = True
                    alive &= ~(touched[:, None] | touched[nbrs])
                    any_fault = True
            # DuplicateLinks: accounting only, no mask effect
        down = self.crashed_at(rnd)
        if down.any():
            # fail-stop: a crashed replica neither contributes state
            # (peers pulling it substitute their own rows) nor pulls
            alive &= ~(down[:, None] | down[nbrs])
            any_fault = True
        if not any_fault:
            # keep the cache: periodic faults (SlowShard, DelayLinks)
            # alternate masked/unmasked rounds with RECURRING content —
            # the cached object keeps identity stable across the cycle
            return None
        alive = symmetrize_edge_mask(nbrs, alive)
        key = alive.tobytes()
        if self._mask_cache is not None and self._mask_cache[0] == key:
            return self._mask_cache[1]
        self._mask_cache = (key, alive)
        return alive

    def masks(self, start: int, stop: int) -> np.ndarray:
        """``bool[stop-start, R, K]`` — the stacked per-round masks of a
        window (all-alive planes where no fault is active), the operand
        of ``ops.fused.fused_chaos_rounds``."""
        if stop <= start:
            raise ValueError(f"empty window [{start}, {stop})")
        out = np.ones(
            (stop - start,) + tuple(self.neighbors.shape), dtype=bool
        )
        for t, rnd in enumerate(range(start, stop)):
            m = self.mask_at(rnd)
            if m is not None:
                out[t] = m
        return out

    def rebase(self, n_replicas: int, neighbors) -> "ChaosSchedule":
        """The same timeline re-compiled for a CHANGED membership
        (``ChaosRuntime.sync_membership``): crash/restore events naming
        a replica outside the new extent are dropped as pairs (a
        departed replica can neither crash nor restore), windowed
        events re-derive their masks from the new topology naturally.
        Determinism is preserved — the same seed drives the new extent's
        draws, so a replay that re-bases at the same round reproduces
        the same masks."""
        n = int(n_replicas)
        dropped = {
            ev.replica for ev in self.events
            if isinstance(ev, (Crash, Restore)) and ev.replica >= n
        }
        events = tuple(
            ev for ev in self.events
            if not (isinstance(ev, (Crash, Restore))
                    and ev.replica in dropped)
        )
        return ChaosSchedule(n, neighbors, events, seed=self.seed)

    def describe(self) -> dict:
        """Plain-data timeline summary (CLI / bench artifact embedding)."""
        return {
            "n_replicas": self.n_replicas,
            "seed": self.seed,
            "horizon": self.horizon,
            "events": [
                {"kind": type(ev).__name__, **ev._asdict()}
                for ev in self.events
            ],
        }


# ---------------------------------------------------------------------------
# nemesis presets
# ---------------------------------------------------------------------------

#: canonical preset names (CLI spelling; underscores accepted too).
#: These are the CRASH/PARTITION-class presets: every one upholds the
#: full ``run_harness`` invariant suite (inflation + post-heal
#: bit-equality) with no repair layer attached.
PRESETS = ("ring-cut", "rolling-crash", "flaky-links", "slow-shard",
           "delay-links")

#: CORRUPTION-class presets (silent state mutation — a different fault
#: class: without the AAE layer attached nothing detects them and the
#: fixed point is NOT the fault-free one; see the fault-model table in
#: docs/RESILIENCE.md). Soaked via ``chaos.invariants.run_aae_harness``
#: / ``lasp_tpu aae``, never the plain invariant harness.
CORRUPTION_PRESETS = ("bit-rot", "corrupt-partition")


def nemesis(preset: str, n_replicas: int, neighbors, *, seed: int = 0,
            rounds: int = 12, start: int = 2, **kwargs) -> ChaosSchedule:
    """Build a preset nemesis schedule — the soak verbs' vocabulary:

    - ``ring-cut``: a 2-way (``n_groups``) partition for ``rounds``
      rounds, then heal — the classic split-brain/merge.
    - ``rolling-crash``: ``crashes`` replicas fail-stop one after
      another, each down for ``down`` rounds then restored from
      ``source`` (bottom by default) — the rolling-restart nemesis.
    - ``flaky-links``: every link drops with ``drop_rate`` per round
      for ``rounds`` rounds, plus a ``DuplicateLinks`` overlay — lossy,
      at-least-once fabric.
    - ``slow-shard``: shard ``shard`` of ``n_shards`` only exchanges
      every ``period``-th round — the straggler device.
    - ``delay-links``: a ``frac`` subset of links buffers deliveries
      ``delay`` rounds — cross-DC latency skew.

    All presets are deterministic in ``(seed, arguments)`` and heal by
    ``schedule.horizon``; extra ``kwargs`` override the preset's knobs.
    """
    name = preset.replace("_", "-")
    n = int(n_replicas)
    stop = start + int(rounds)
    if name == "ring-cut":
        ev = [Partition(start, stop, int(kwargs.pop("n_groups", 2)))]
    elif name == "rolling-crash":
        crashes = int(kwargs.pop("crashes", min(3, max(1, n // 8))))
        down = int(kwargs.pop("down", max(2, rounds // 3)))
        stagger = int(kwargs.pop("stagger", max(1, down // 2)))
        source = kwargs.pop("source", "bottom")
        rng = np.random.RandomState(seed)
        victims = rng.choice(n, size=min(crashes, n), replace=False)
        ev = []
        for i, r in enumerate(victims):
            at = start + i * stagger
            ev.append(Crash(at, int(r)))
            ev.append(Restore(at + down, int(r), source=source))
    elif name == "flaky-links":
        drop = float(kwargs.pop("drop_rate", 0.25))
        dup = float(kwargs.pop("duplicate_frac", 0.2))
        ev = [FlakyLinks(start, stop, drop),
              DuplicateLinks(start, stop, dup)]
    elif name == "slow-shard":
        ev = [SlowShard(
            start, stop,
            shard=int(kwargs.pop("shard", 0)),
            n_shards=int(kwargs.pop("n_shards", 4)),
            period=int(kwargs.pop("period", 3)),
        )]
    elif name == "delay-links":
        ev = [DelayLinks(
            start, stop,
            frac=float(kwargs.pop("frac", 0.3)),
            delay=int(kwargs.pop("delay", 2)),
        )]
    elif name == "bit-rot":
        # ambient silent corruption: one seeded injection every
        # `every`-th round of the window (all three mutation kinds
        # cycle unless pinned) — the fault class only the AAE layer
        # can detect (docs/RESILIENCE.md "Active anti-entropy")
        every = int(kwargs.pop("every", 2))
        n_rows = int(kwargs.pop("n_rows", 1))
        kind = kwargs.pop("kind", None)
        if kind is not None:
            ev = [BitRot(start, stop, every=every, kind=kind,
                         n_rows=n_rows)]
        else:
            ev = [
                BitRot(start + i, stop, every=every * 3, kind=k,
                       n_rows=n_rows)
                for i, k in enumerate(CORRUPTION_KINDS)
                if start + i < stop
            ]
    elif name == "corrupt-partition":
        # corruption INSIDE a split brain: detection and quorum repair
        # must both stay confined to the corrupt row's component — the
        # combined nemesis the acceptance drill runs
        n_groups = int(kwargs.pop("n_groups", 2))
        n_rows = int(kwargs.pop("n_rows", 1))
        ev = [Partition(start, stop, n_groups),
              CorruptRows(start + 1, kind="bitflip", n_rows=n_rows),
              CorruptRows(min(start + 3, stop - 1), kind="rollback",
                          n_rows=n_rows)]
    else:
        raise ValueError(
            f"unknown nemesis preset {preset!r} "
            f"(known: {PRESETS + CORRUPTION_PRESETS})"
        )
    if kwargs:
        raise TypeError(
            f"nemesis({name!r}): unknown options {sorted(kwargs)}"
        )
    return ChaosSchedule(n, neighbors, ev, seed=seed)
